"""Battery cycle-degradation: rainflow counting + damage accumulation.

Re-implements the behavior of the storagevet battery degradation module
(SURVEY.md §2.8 BatteryTech surface: ``incl_cycle_degrade``,
``degrade_data``, ``degrade_perc``, ``degraded_energy_capacity()``,
``calc_degradation``; driven from dervet/MicrogridDER/Battery.py:69-179):

* rainflow cycle counting (ASTM E1049 half/full-cycle rules) on the
  normalized state-of-charge profile of each optimization window — the
  reference depends on the ``rainflow`` package (requirements.txt:21,
  hooks/hook-rainflow.py)
* each counted cycle of depth d contributes ``count / N(d)`` of life,
  where N(d) is the 'Cycle Life Value' for the smallest 'Cycle Depth
  Upper Limit' >= d in the battery's cycle-life table
  (data/battery_cycle_life.csv format)
* calendar fade adds ``yearly_degrade`` percent per year, pro-rated by
  window length
* when remaining capacity falls to ``state_of_health`` x nameplate the
  system is replaced (degradation resets) if ``replaceable``, and the
  year is recorded for the financial layer's failure-year machinery
  (reference Battery.py:87-110).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import pandas as pd


def turning_points(x: np.ndarray) -> np.ndarray:
    """Strip monotone runs and plateaus to local extrema (keep endpoints)."""
    x = np.asarray(x, np.float64)
    # collapse repeated values first so plateaus cannot mask extrema
    x = x[np.concatenate([[True], np.diff(x) != 0])]
    if len(x) < 3:
        return x
    d = np.diff(x)
    keep = np.ones(len(x), bool)
    keep[1:-1] = d[:-1] * d[1:] < 0
    return x[keep]


def rainflow(x: np.ndarray) -> List[Tuple[float, float]]:
    """ASTM E1049 rainflow counting.

    Returns ``(range, count)`` pairs with count 1.0 for full cycles and
    0.5 for residual half cycles.
    """
    pts = list(turning_points(np.asarray(x, np.float64)))
    stack: List[float] = []
    out: List[Tuple[float, float]] = []
    for p in pts:
        stack.append(p)
        while len(stack) >= 3:
            X = abs(stack[-2] - stack[-1])
            Y = abs(stack[-3] - stack[-2])
            if X < Y:
                break
            if len(stack) == 3:
                # half cycle on the leading residue
                out.append((Y, 0.5))
                stack.pop(0)
            else:
                out.append((Y, 1.0))
                last = stack.pop()
                stack.pop()
                stack.pop()
                stack.append(last)
    for i in range(len(stack) - 1):
        out.append((abs(stack[i] - stack[i + 1]), 0.5))
    return [(r, c) for r, c in out if r > 0]


class CycleDegradation:
    """Depth-binned cycle-life damage model."""

    def __init__(self, cycle_life: pd.DataFrame):
        cols = {str(c).strip().lower(): c for c in cycle_life.columns}
        depth_col = next(c for k, c in cols.items() if "depth" in k)
        life_col = next(c for k, c in cols.items() if "life" in k)
        df = cycle_life.sort_values(depth_col)
        self.depths = df[depth_col].to_numpy(np.float64)
        self.lives = df[life_col].to_numpy(np.float64)

    def life_at(self, depth: float) -> float:
        """Cycle life at a given depth-of-cycle fraction: smallest upper
        limit bin containing the depth (last bin for deeper cycles)."""
        i = int(np.searchsorted(self.depths, depth, side="left"))
        i = min(i, len(self.lives) - 1)
        return float(self.lives[i])

    def damage(self, soc_profile: np.ndarray) -> float:
        """Fractional life consumed by one window's normalized SOC profile."""
        total = 0.0
        for rng, count in rainflow(soc_profile):
            life = self.life_at(rng)
            if life > 0:
                total += count / life
        return total
