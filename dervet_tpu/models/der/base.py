"""DER base class: the component contract for the LP-block architecture.

Replaces the reference's CVXPY-variable DER base
(storagevet.Technology.DistributedEnergyResource.DER surface, SURVEY.md
§2.8): instead of returning CVXPY expression trees from
``initialize_variables``/``constraints``/``objective_function``, each DER
emits named variable blocks, structured constraint rows, and linear cost
vectors into an :class:`~dervet_tpu.ops.lp.LPBuilder`, once per
optimization window.  Dispatch results come back as named slices of the
batched solution tensor.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from ...ops.lp import LPBuilder, VarRef
from ...scenario.window import WindowContext


def integer_size(value: float, upper: float = 0.0) -> float:
    """Snap a solved CONTINUOUS size variable onto the reference's integer
    grid (every reference size var is ``cvx.Variable(integer=True)`` —
    ESSSizing.py:83-138, IntermittentResourceSizing.py:71,
    RotatingGeneratorSizing.py:61).  Ceil preserves feasibility of every
    capacity-type constraint the relaxation satisfied; when a finite user
    upper bound forbids rounding up, fall back to its integer floor —
    exactly the largest value the reference's integer solver could pick.
    The dispatch windows then RE-SOLVE at the snapped ratings (one extra
    batched solve), so reported dispatch is consistent with reported
    sizes (VERDICT r3 #6)."""
    v = float(np.ceil(value - 1e-6))
    if upper and v > upper:
        v = float(np.floor(upper + 1e-9))
    return v


class DER:
    """Base distributed energy resource."""

    technology_type = "DER"

    def __init__(self, tag: str, der_id: str, keys: Dict, scenario: Dict):
        self.tag = tag
        self.id = der_id or ""
        # the reference lowercases DER names in every output column: input
        # name=ES yields 'BATTERY: es ...' in its frozen goldens, name=Battery
        # yields 'BATTERY: battery Discharge (kW)' (test_technology_features)
        self.name = str(keys.get("name", tag)).lower()
        self.dt = float(scenario.get("dt", 1))
        self.keys = keys
        self.scenario = scenario
        # full-year dispatch results, filled by the scenario loop
        self.variables_df: Optional[pd.DataFrame] = None

    # ---------- identity / column naming (matches reference outputs) ----
    @property
    def unique_tech_id(self) -> str:
        return f"{self.tag.upper()}: {self.name}"

    def col(self, quantity: str) -> str:
        """Reference output column name, e.g. 'BATTERY: es Discharge (kW)'."""
        return f"{self.unique_tech_id} {quantity}"

    # ---------- LP assembly --------------------------------------------
    def vname(self, var: str) -> str:
        return f"{self.tag}-{self.id or '1'}/{var}"

    def build(self, b: LPBuilder, ctx: WindowContext) -> None:
        """Register variables/constraints/costs for one window.

        Implementations must create identical *structure* for equal window
        length T (data may differ) so same-length windows share one
        compiled solver and batch onto the TPU together.
        """
        raise NotImplementedError

    # ---------- POI interface ------------------------------------------
    def power_terms(self, b: LPBuilder) -> List[Tuple[VarRef, float]]:
        """Decision-variable contributions to net power at the POI.

        Returns ``(ref, sign)`` pairs; sign +1 injects power to the grid
        (discharge/generation), -1 consumes (charge/load).
        """
        return []

    def fixed_load(self, ctx: WindowContext) -> Optional[np.ndarray]:
        """Constant (non-decision) load profile in kW, or None."""
        return None

    def soe_term(self, b: LPBuilder) -> Optional[VarRef]:
        """State-of-energy block for aggregate energy requirements."""
        return None

    def market_headroom(self, b: LPBuilder, direction: str
                        ) -> Tuple[List[Tuple[VarRef, float]], float]:
        """Available capacity for market services in kW as an affine
        expression ``const + sum(coef * var)``.

        ``direction`` 'up' = extra injection capability (raise discharge /
        cut charge); 'down' = extra absorption.  Default: cannot
        participate (reference: base DER zero-valued up/down schedules,
        SURVEY.md §2.8 ``get_charge_up/down_schedule``).
        """
        return [], 0.0

    # full-horizon report series for the POI totals (post-solve)
    def load_series(self) -> Optional[np.ndarray]:
        """Effective load (kW) this DER contributes, incl. fixed loads."""
        return None

    def generation_series(self) -> Optional[np.ndarray]:
        """Generation (kW) this DER contributes (storage reports separately)."""
        return None

    # ---------- results -------------------------------------------------
    def store_dispatch(self, index: pd.DatetimeIndex, values: Dict[str, np.ndarray]):
        """Stash full-year dispatch arrays (keyed by short var name)."""
        self.variables_df = pd.DataFrame(values, index=index)

    def timeseries_report(self) -> pd.DataFrame:
        idx = self.variables_df.index if self.variables_df is not None else None
        return pd.DataFrame(index=idx)

    def monthly_report(self) -> pd.DataFrame:
        return pd.DataFrame()

    def proforma_report(self, opt_years: List[int],
                        apply_inflation_rate_func=None,
                        fill_forward_func=None) -> Optional[pd.DataFrame]:
        """Per-year cost/benefit rows keyed by pd.Period years (reference:
        DER.proforma_report surface; CAPEX year handled by the CBA)."""
        return None

    def owns_asset(self) -> bool:
        """False when the host pays for output but does not own the asset
        (PV PPA): the CBA then skips MACRS / replacement / decommissioning
        / salvage for this DER."""
        return True

    def proforma_growth_rates(self) -> Dict[str, float]:
        """Escalation rates for this DER's proforma columns in
        fill-forward years (default: flat)."""
        return {}

    def get_capex(self) -> float:
        return 0.0

    def sizing_summary(self) -> Dict:
        return {}

    # ---------- lifecycle (DERExtension surface) -----------------------
    # (reference: dervet/MicrogridDER/DERExtension.py — construction /
    # operation years, failure years, replacement, escalation, ECC, MACRS)
    def _lifecycle_int(self, key: str, default: int = 0) -> int:
        try:
            return int(float(self.keys.get(key, default) or default))
        except (TypeError, ValueError):
            return default

    @property
    def construction_year(self) -> int:
        return self._lifecycle_int("construction_year")

    @property
    def operation_year(self) -> int:
        return self._lifecycle_int("operation_year")

    @property
    def expected_lifetime(self) -> int:
        return self._lifecycle_int("expected_lifetime")

    @property
    def replaceable(self) -> bool:
        return bool(self.keys.get("replaceable", False))

    @property
    def replacement_construction_time(self) -> int:
        return max(self._lifecycle_int("replacement_construction_time", 1), 1)

    @property
    def escalation_rate(self) -> float:
        return float(self.keys.get("ter", 0) or 0) / 100.0

    @property
    def ecc_perc(self) -> float:
        return float(self.keys.get("ecc%", 0) or 0) / 100.0

    def replacement_cost(self) -> float:
        """Cost of replacing this DER (reference: rcost/rcost_kW/rcost_kWh
        dot product, ESSSizing.py:438-444; subclasses refine)."""
        return float(self.keys.get("rcost", 0) or 0)

    def set_failure_years(self, end_year: int,
                          start_year: Optional[int] = None) -> List[int]:
        """Years this equipment fails, incl. periodic replacements
        (reference: DERExtension.set_failure_years, :86-114).  A missing
        operation_year means operation starts at the project start."""
        lifetime = self.expected_lifetime
        if not lifetime:
            self.failure_years: List[int] = []
            self.last_operation_year = end_year
            return self.failure_years
        op = self.operation_year or start_year or end_year
        last = op + lifetime - 1
        years = []
        if last <= end_year:
            years.append(last)
        if self.replaceable:
            # the final replacement's last operating year lands at or past
            # the analysis end (reference DERExtension.py:106-112) — salvage
            # value keys off how far it outlives the project
            nxt = last + lifetime
            while nxt < end_year:
                years.append(nxt)
                nxt += lifetime
            self.last_operation_year = nxt
        else:
            self.last_operation_year = last
        self.failure_years = sorted(set(years))
        return self.failure_years

    def equipment_lifetime_row(self, end_year: int,
                               start_year: Optional[int] = None) -> Dict[str, int]:
        """Rows for the equipment_lifetimes report (golden columns:
        Beginning of Life / Operation Begins / End of Life)."""
        self.set_failure_years(end_year, start_year)
        return {"Beginning of Life": self.construction_year or self.operation_year,
                "Operation Begins": self.operation_year,
                "End of Life": self.last_operation_year}

    def operational(self, year: int) -> bool:
        op_year = self.operation_year
        if op_year and year < op_year:
            return False
        last = getattr(self, "last_operation_year", None)
        if last is not None and not self.replaceable and \
                self.expected_lifetime and year > last:
            return False
        return True

    def being_sized(self) -> bool:
        return False
