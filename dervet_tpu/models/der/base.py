"""DER base class: the component contract for the LP-block architecture.

Replaces the reference's CVXPY-variable DER base
(storagevet.Technology.DistributedEnergyResource.DER surface, SURVEY.md
§2.8): instead of returning CVXPY expression trees from
``initialize_variables``/``constraints``/``objective_function``, each DER
emits named variable blocks, structured constraint rows, and linear cost
vectors into an :class:`~dervet_tpu.ops.lp.LPBuilder`, once per
optimization window.  Dispatch results come back as named slices of the
batched solution tensor.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from ...ops.lp import LPBuilder, VarRef
from ...scenario.window import WindowContext


class DER:
    """Base distributed energy resource."""

    technology_type = "DER"

    def __init__(self, tag: str, der_id: str, keys: Dict, scenario: Dict):
        self.tag = tag
        self.id = der_id or ""
        self.name = str(keys.get("name", tag))
        self.dt = float(scenario.get("dt", 1))
        self.keys = keys
        self.scenario = scenario
        # full-year dispatch results, filled by the scenario loop
        self.variables_df: Optional[pd.DataFrame] = None

    # ---------- identity / column naming (matches reference outputs) ----
    @property
    def unique_tech_id(self) -> str:
        return f"{self.tag.upper()}: {self.name}"

    def col(self, quantity: str) -> str:
        """Reference output column name, e.g. 'BATTERY: es Discharge (kW)'."""
        return f"{self.unique_tech_id} {quantity}"

    # ---------- LP assembly --------------------------------------------
    def vname(self, var: str) -> str:
        return f"{self.tag}-{self.id or '1'}/{var}"

    def build(self, b: LPBuilder, ctx: WindowContext) -> None:
        """Register variables/constraints/costs for one window.

        Implementations must create identical *structure* for equal window
        length T (data may differ) so same-length windows share one
        compiled solver and batch onto the TPU together.
        """
        raise NotImplementedError

    # ---------- POI interface ------------------------------------------
    def power_terms(self, b: LPBuilder) -> List[Tuple[VarRef, float]]:
        """Decision-variable contributions to net power at the POI.

        Returns ``(ref, sign)`` pairs; sign +1 injects power to the grid
        (discharge/generation), -1 consumes (charge/load).
        """
        return []

    def fixed_load(self, ctx: WindowContext) -> Optional[np.ndarray]:
        """Constant (non-decision) load profile in kW, or None."""
        return None

    def soe_term(self, b: LPBuilder) -> Optional[VarRef]:
        """State-of-energy block for aggregate energy requirements."""
        return None

    def market_headroom(self, b: LPBuilder, direction: str
                        ) -> Tuple[List[Tuple[VarRef, float]], float]:
        """Available capacity for market services in kW as an affine
        expression ``const + sum(coef * var)``.

        ``direction`` 'up' = extra injection capability (raise discharge /
        cut charge); 'down' = extra absorption.  Default: cannot
        participate (reference: base DER zero-valued up/down schedules,
        SURVEY.md §2.8 ``get_charge_up/down_schedule``).
        """
        return [], 0.0

    # full-horizon report series for the POI totals (post-solve)
    def load_series(self) -> Optional[np.ndarray]:
        """Effective load (kW) this DER contributes, incl. fixed loads."""
        return None

    def generation_series(self) -> Optional[np.ndarray]:
        """Generation (kW) this DER contributes (storage reports separately)."""
        return None

    # ---------- results -------------------------------------------------
    def store_dispatch(self, index: pd.DatetimeIndex, values: Dict[str, np.ndarray]):
        """Stash full-year dispatch arrays (keyed by short var name)."""
        self.variables_df = pd.DataFrame(values, index=index)

    def timeseries_report(self) -> pd.DataFrame:
        idx = self.variables_df.index if self.variables_df is not None else None
        return pd.DataFrame(index=idx)

    def monthly_report(self) -> pd.DataFrame:
        return pd.DataFrame()

    def proforma_report(self, opt_years: List[int],
                        apply_inflation_rate_func=None,
                        fill_forward_func=None) -> Optional[pd.DataFrame]:
        """Per-year cost/benefit rows keyed by pd.Period years (reference:
        DER.proforma_report surface; CAPEX year handled by the CBA)."""
        return None

    def get_capex(self) -> float:
        return 0.0

    def sizing_summary(self) -> Dict:
        return {}

    # ---------- lifecycle (DERExtension surface) -----------------------
    def operational(self, year: int) -> bool:
        op_year = int(self.keys.get("operation_year", 0) or 0)
        return year >= op_year if op_year else True

    def being_sized(self) -> bool:
        return False
