"""DER base class: the component contract for the LP-block architecture.

Replaces the reference's CVXPY-variable DER base
(storagevet.Technology.DistributedEnergyResource.DER surface, SURVEY.md
§2.8): instead of returning CVXPY expression trees from
``initialize_variables``/``constraints``/``objective_function``, each DER
emits named variable blocks, structured constraint rows, and linear cost
vectors into an :class:`~dervet_tpu.ops.lp.LPBuilder`, once per
optimization window.  Dispatch results come back as named slices of the
batched solution tensor.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import pandas as pd

from ...ops.lp import LPBuilder


class DER:
    """Base distributed energy resource."""

    technology_type = "DER"

    def __init__(self, tag: str, der_id: str, keys: Dict, scenario: Dict):
        self.tag = tag
        self.id = der_id
        self.name = str(keys.get("name", tag))
        self.dt = float(scenario.get("dt", 1))
        self.keys = keys
        # full-year dispatch results, filled by the scenario loop
        self.variables_df: Optional[pd.DataFrame] = None

    # ---------- identity / column naming (matches reference outputs) ----
    @property
    def unique_tech_id(self) -> str:
        return f"{self.tag.upper()}: {self.name}"

    # ---------- LP assembly --------------------------------------------
    def vname(self, var: str) -> str:
        return f"{self.tag}-{self.id or '1'}/{var}"

    def build(self, b: LPBuilder, T: int, data: Dict) -> None:
        """Register variables/constraints/costs for a T-step window.

        ``data`` carries per-window arrays (prices, profiles) and scalars
        (annuity_scalar).  Implementations must create identical structure
        for equal T so windows can share one compiled solver.
        """
        raise NotImplementedError

    # power contributions to the POI balance, as (varname, sign) pairs
    def generation_vars(self):
        return []

    def load_vars(self):
        return []

    # state of energy contribution (varname) or None
    def soe_var(self) -> Optional[str]:
        return None

    # ---------- results -------------------------------------------------
    def store_dispatch(self, index: pd.DatetimeIndex, values: Dict[str, np.ndarray]):
        """Stash full-year dispatch arrays (keyed by short var name)."""
        self.variables_df = pd.DataFrame(values, index=index)

    def timeseries_report(self) -> pd.DataFrame:
        return pd.DataFrame(index=self.variables_df.index)

    def monthly_report(self) -> pd.DataFrame:
        return pd.DataFrame()

    def proforma_report(self, opt_years, results: pd.DataFrame) -> Optional[pd.DataFrame]:
        return None

    def get_capex(self) -> float:
        return 0.0

    def sizing_summary(self) -> Dict:
        return {}

    # operational window (DERExtension surface: operation_year gating)
    def operational(self, year: int) -> bool:
        op_year = int(self.keys.get("operation_year", 0) or 0)
        return year >= op_year if op_year else True
