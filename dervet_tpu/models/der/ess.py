"""Energy-storage physics as LP blocks (EnergyStorage base + Battery).

Re-implements the behavior of the reference's storagevet
``Technology.EnergyStorage`` + ``BatteryTech.Battery`` + dervet
``MicrogridDER/ESSSizing.py`` + ``MicrogridDER/Battery.py`` (SURVEY.md
§2.4/§2.8) as structured constraint rows instead of CVXPY expressions:

* variables per window: ``ene`` (end-of-step state of energy, kWh),
  ``ch`` (charging power, kW), ``dis`` (discharging power, kW)
* SOE evolution with round-trip efficiency on charge and self-discharge,
  window boundary condition pinning first/last SOE to the target
  (windows start and end at ``soc_target`` — this is what makes windows
  independent and therefore batchable on the scenario axis)
* bounds from rated capacities and SOC limits
* optional daily cycle-count limit as per-day energy rows

Inputs are the reference's Battery tag keys (percent-valued keys are
converted to fractions here).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd
import scipy.sparse as sp

from ...ops.lp import LPBuilder, VarRef
from ...scenario.window import WindowContext
from ...utils.errors import ParameterError, TellUser
from .base import DER


class EnergyStorage(DER):
    """Generic electric energy-storage system (reference: storagevet
    EnergyStorage surface, SURVEY.md §2.8)."""

    technology_type = "Energy Storage System"

    def __init__(self, tag: str, der_id: str, keys: Dict, scenario: Dict):
        super().__init__(tag, der_id, keys, scenario)
        g = lambda k, d=0.0: float(keys.get(k, d) or 0.0)
        self.rte = g("rte", 100.0) / 100.0
        self.sdr = g("sdr") / 100.0            # self-discharge, fraction/step
        self.llsoc = g("llsoc") / 100.0
        self.ulsoc = g("ulsoc", 100.0) / 100.0
        self.soc_target = g("soc_target", 50.0) / 100.0
        self.ch_max_rated = g("ch_max_rated")
        self.dis_max_rated = g("dis_max_rated")
        self.ch_min_rated = g("ch_min_rated")
        self.dis_min_rated = g("dis_min_rated")
        self.ene_max_rated = g("ene_max_rated")
        self.duration_max = g("duration_max")
        self.daily_cycle_limit = g("daily_cycle_limit")
        self.hp = g("hp")                       # house power (kW, constant)
        self.variable_om = g("OMexpenses") / 1000.0   # $/MWh -> $/kWh
        self.fixed_om_per_kw = g("fixedOM")           # $/kW-yr on discharge
        self.ccost = g("ccost")
        self.ccost_kw = g("ccost_kw")
        self.ccost_kwh = g("ccost_kwh")
        self.incl_binary = bool(scenario.get("binary", False))
        if (self.ch_min_rated or self.dis_min_rated) and not self.incl_binary:
            TellUser.warning(f"{self.name}: nonzero ch/dis minimums require the "
                             "binary formulation; ignored in the LP relaxation")
        # startup costs ride the binary on/off indicators (reference:
        # EnergyStorage incl_startup + p_start_ch/p_start_dis surface,
        # wired through ESSSizing.py:389-396)
        self.incl_startup = bool(keys.get("startup", False))
        self.p_start_ch = g("p_start_ch")
        self.p_start_dis = g("p_start_dis")
        if self.incl_startup and not self.incl_binary:
            TellUser.warning(
                f"{self.name}: startup=1 requires the binary formulation "
                "(scenario binary=1); startup costs are NOT applied")
        # fraction of rated energy usable (degradation hooks update this)
        self.soh = 1.0
        # sizing: a zero rating is a size decision variable (reference:
        # ESSSizing.py:82-138 swaps zeros for CVXPY integer Variables with
        # user min/max bounds; here a continuous scalar LP variable)
        self.sizing_ene = self.ene_max_rated == 0
        self.sizing_ch = self.ch_max_rated == 0
        self.sizing_dis = self.dis_max_rated == 0
        self.user_bounds = {
            "ene": (g("user_ene_rated_min"), g("user_ene_rated_max")),
            "ch": (g("user_ch_rated_min"), g("user_ch_rated_max")),
            "dis": (g("user_dis_rated_min"), g("user_dis_rated_max")),
        }
        # per-window user TS limits actually applied, echoed into the
        # output timeseries: column stem -> {window label: Series}
        self._ts_user_limits: Dict[str, Dict[int, pd.Series]] = {}

    # ---------------- capacity accessors (sizing overrides later) ------
    def energy_capacity(self) -> float:
        return self.ene_max_rated

    def charge_capacity(self) -> float:
        return self.ch_max_rated

    def discharge_capacity(self) -> float:
        return self.dis_max_rated

    def operational_max_energy(self) -> float:
        return self.ulsoc * self.soh * self.energy_capacity()

    def operational_min_energy(self) -> float:
        return self.llsoc * self.soh * self.energy_capacity()

    @property
    def ene_target(self) -> float:
        return self.soc_target * self.soh * self.energy_capacity()

    # ---------------- LP assembly --------------------------------------
    def being_sized(self) -> bool:
        return self.sizing_ene or self.sizing_ch or self.sizing_dis

    def _size_var(self, b: LPBuilder, which: str):
        lo, hi = self.user_bounds[which]
        return b.var(self.vname(f"size_{which}"), 1, lb=max(lo, 0.0),
                     ub=hi if hi > 0 else np.inf)

    def build(self, b: LPBuilder, ctx: WindowContext) -> None:
        T, dt = ctx.T, ctx.dt
        if self.being_sized():
            self._build_sizing(b, ctx)
            return
        e_max = self.operational_max_energy()
        e_min = self.operational_min_energy()
        e0 = ctx.carry.get(self.vname("soe0"), self.ene_target)

        ene = b.var(self.vname("ene"), T, lb=e_min, ub=e_max)
        ch = b.var(self.vname("ch"), T, lb=0.0, ub=self.charge_capacity())
        dis = b.var(self.vname("dis"), T, lb=0.0, ub=self.discharge_capacity())
        self._ts_limit_bounds(b, ctx, ene, ch, dis, e_min, e_max)
        if self.incl_binary:
            self._binary_onoff_rows(b, ctx, ch, dis)

        # BEGIN-of-step SOE convention (verified against the Usecase2 step2
        # golden to 1e-10): ene[t+1] = ene[t]*(1-sdr) + rte*dt*ch[t] -
        # dt*dis[t]; ene[0] pinned to the window-entry target and the
        # POST-last-step state pinned back to the target (the golden's
        # implied post-window SOE is exactly soc_target*rating every
        # month).  An end-of-step convention makes the min-SOE floor bind
        # AT the peak hour instead of after it and loses ~20% of
        # demand-charge savings vs the reference.
        soe_terms, final_terms = self._soe_rows(ene, ch, dis, T, dt)
        rhs = np.zeros(T)
        rhs[0] = e0
        b.add_rows(self.vname("soe"), soe_terms, "eq", rhs)
        b.add_rows(self.vname("soe_final"), final_terms, "eq",
                   np.array([self.ene_target]))

        if self.daily_cycle_limit > 0:
            self._daily_cycle_rows(b, ctx, dis)

        # operating costs
        if self.variable_om:
            b.add_cost(dis, self.variable_om * dt * ctx.annuity_scalar,
                       label=f"{self.name} var_om")
        if self.fixed_om_per_kw:
            b.add_const_cost(self.fixed_om_per_kw * self.discharge_capacity()
                             * ctx.annuity_scalar * (T * dt) / 8760.0,
                             label=f"{self.name} fixed_om")

    def _build_sizing(self, b: LPBuilder, ctx: WindowContext) -> None:
        """Sizing formulation: zero ratings become scalar size variables;
        capacity bounds/SOE targets become rows against them (reference:
        ESSSizing.py:82-138 effective-SOE expressions + bound constraints;
        continuous relaxation of the integer sizes per SURVEY §7)."""
        T, dt = ctx.T, ctx.dt
        one = np.ones((T, 1))
        ene = b.var(self.vname("ene"), T, lb=0.0)
        ch = b.var(self.vname("ch"), T, lb=0.0,
                   ub=np.inf if self.sizing_ch else self.charge_capacity())
        dis = b.var(self.vname("dis"), T, lb=0.0,
                    ub=np.inf if self.sizing_dis else self.discharge_capacity())
        if self.sizing_ene:
            size_e = self._size_var(b, "ene")
            b.add_rows(self.vname("ene_ub"),
                       [(ene, 1.0), (size_e, -self.ulsoc * self.soh * one)],
                       "le", 0.0)
            if self.llsoc > 0:
                b.add_rows(self.vname("ene_lb"),
                           [(ene, 1.0), (size_e, -self.llsoc * self.soh * one)],
                           "ge", 0.0)
            target_term = [(size_e, np.full((1, 1), -self.soc_target * self.soh))]
            b.add_cost(size_e, self.ccost_kwh, label=f"{self.name}capex")
        else:
            b.set_bounds(ene, lb=self.operational_min_energy(),
                         ub=self.operational_max_energy())
            target_term = []
        if self.sizing_ch and self.sizing_dis:
            # both ratings zero: size ONE power cap shared by charge and
            # discharge (reference: ESSSizing.py:97-106 sets
            # dis_max_rated = ch_max_rated)
            size_p = self._size_var(b, "dis")
            b.add_rows(self.vname("ch_ub"), [(ch, 1.0), (size_p, -one)],
                       "le", 0.0)
            b.add_rows(self.vname("dis_ub"), [(dis, 1.0), (size_p, -one)],
                       "le", 0.0)
            b.add_cost(size_p, self.ccost_kw, label=f"{self.name}capex")
            # NOTE: no fixed-O&M on the sized rating — the reference
            # evaluates fixedOM * dis_max_rated before ESSSizing swaps the
            # zero rating for a variable, so sized DERs carry zero fixed
            # O&M in the sizing objective (verified against the Usecase1
            # size golden: including it undershoots the size by 7%)
        elif self.sizing_ch:
            size_c = self._size_var(b, "ch")
            b.add_rows(self.vname("ch_ub"), [(ch, 1.0), (size_c, -one)],
                       "le", 0.0)
        elif self.sizing_dis:
            size_d = self._size_var(b, "dis")
            b.add_rows(self.vname("dis_ub"), [(dis, 1.0), (size_d, -one)],
                       "le", 0.0)
            b.add_cost(size_d, self.ccost_kw, label=f"{self.name}capex")
        # ts limits still apply to non-sized ratings; the sized rating's
        # limits log an error and are dropped (reference ESSSizing.py:88-116).
        # Applied AFTER the static bound assignments above so per-timestep
        # limits are not overwritten.
        self._ts_limit_bounds(b, ctx, ene, ch, dis,
                              self.operational_min_energy(),
                              self.operational_max_energy())
        if self.ccost:
            b.add_const_cost(self.ccost, label=f"{self.name}capex")
        if self.duration_max and self.sizing_ene and self.sizing_dis:
            b.add_rows(self.vname("duration_max"),
                       [(b[self.vname("size_ene")], np.ones((1, 1))),
                        (b[self.vname("size_dis")],
                         np.full((1, 1), -self.duration_max))], "le", 0.0)

        # BEGIN-of-step SOE with both the window ENTRY and the
        # post-last-step state pinned to soc_target * size (same convention
        # as the fixed-size build, with the size variable supplying the
        # target)
        first = sp.csr_matrix((np.ones(1), (np.zeros(1, int), np.zeros(1, int))),
                              shape=(T, 1))
        soe_terms, final_terms = self._soe_rows(ene, ch, dis, T, dt)
        if target_term:
            ref, coef = target_term[0]
            soe_terms.append((ref, first * float(coef[0, 0])))
            b.add_rows(self.vname("soe"), soe_terms, "eq", np.zeros(T))
            b.add_rows(self.vname("soe_final"), final_terms + [(ref, coef)],
                       "eq", 0.0)
        else:
            rhs = np.zeros(T)
            rhs[0] = self.ene_target
            b.add_rows(self.vname("soe"), soe_terms, "eq", rhs)
            b.add_rows(self.vname("soe_final"), final_terms, "eq",
                       np.array([self.ene_target]))

        if self.daily_cycle_limit > 0:
            if self.sizing_ene:
                # sum_day(dis)*dt <= limit * usable * size_e — linear in the
                # size variable, carried into the sizing LP
                mat = self._daily_sum_matrix(ctx)
                usable = self.daily_cycle_limit * (self.ulsoc - self.llsoc) \
                    * self.soh
                b.add_rows(self.vname("daily_cycle"),
                           [(dis, mat),
                            (b[self.vname("size_ene")],
                             np.full((mat.shape[0], 1), -usable))],
                           "le", 0.0)
            else:
                self._daily_cycle_rows(b, ctx, dis)

        if self.variable_om:
            b.add_cost(dis, self.variable_om * dt * ctx.annuity_scalar,
                       label=f"{self.name} var_om")
        if self.fixed_om_per_kw and not self.sizing_dis:
            b.add_const_cost(self.fixed_om_per_kw * self.discharge_capacity()
                             * ctx.annuity_scalar * (T * dt) / 8760.0,
                             label=f"{self.name} fixed_om")

    def set_size(self, sizes: Dict[str, float]) -> None:
        """Freeze solved size variables into ratings, snapped to the
        reference's integer grid (reference: ESSSizing.set_size with
        ``integer=True`` size vars, applied after the first window —
        MicrogridScenario.py:361-363, ESSSizing.py:83-138)."""
        from .base import integer_size

        self.size_continuous = {k: float(v) for k, v in sizes.items()}
        if "size_ene" in sizes:
            self.ene_max_rated = integer_size(float(sizes["size_ene"]),
                                              self.user_bounds["ene"][1])
            self.sizing_ene = False
        if "size_ch" in sizes:
            self.ch_max_rated = integer_size(float(sizes["size_ch"]),
                                             self.user_bounds["ch"][1])
            self.sizing_ch = False
        if "size_dis" in sizes:
            self.dis_max_rated = integer_size(float(sizes["size_dis"]),
                                              self.user_bounds["dis"][1])
            if self.sizing_ch:      # shared power cap (both were zero)
                self.ch_max_rated = self.dis_max_rated
                self.sizing_ch = False
            self.sizing_dis = False
        cont = ", ".join(f"{k[5:]} {v:.2f}"
                         for k, v in self.size_continuous.items())
        TellUser.info(f"{self.name} sized: {self.ene_max_rated:.1f} kWh, "
                      f"ch {self.ch_max_rated:.1f} kW / "
                      f"dis {self.dis_max_rated:.1f} kW "
                      f"(continuous relaxation: {cont})")

    def _soe_rows(self, ene, ch, dis, T: int, dt: float):
        """Begin-of-step SOE constraint blocks shared by the fixed-size and
        sizing builds: ``(soe_terms, final_terms)`` where soe_terms encode
        ene[t+1] = ene[t]*(1-sdr) + rte*dt*ch[t] - dt*dis[t] (row 0 is the
        entry pin) and final_terms the post-last-step state."""
        diag = sp.diags([np.full(T, 1.0), np.full(T - 1, -(1.0 - self.sdr))],
                        offsets=[0, -1], format="csr")
        sub = sp.diags([np.full(T - 1, 1.0)], offsets=[-1], format="csr")
        soe_terms = [(ene, diag), (ch, sub * (-self.rte * dt)),
                     (dis, sub * dt)]
        last = np.zeros(T)
        last[T - 1] = 1.0
        final_terms = [(ene, sp.csr_matrix(last * (1.0 - self.sdr))),
                       (ch, sp.csr_matrix(last * self.rte * dt)),
                       (dis, sp.csr_matrix(last * -dt))]
        return soe_terms, final_terms

    def _ts_limit_bounds(self, b: LPBuilder, ctx: WindowContext, ene, ch,
                         dis, e_min: float, e_max: float) -> None:
        """Optional per-DER time-series limit columns tighten the variable
        bounds (reference ESSSizing.py:236-262: 'Battery: Charge Max
        (kW)/<id>' etc., gated by incl_ts_*_limits keys; ignored with an
        error log when the corresponding rating is being sized)."""
        tag = self.tag
        spec = [
            ("incl_ts_charge_limits", ch,
             f"{tag}: Charge Min (kW)", f"{tag}: Charge Max (kW)",
             0.0, self.charge_capacity(), self.sizing_ch),
            ("incl_ts_discharge_limits", dis,
             f"{tag}: Discharge Min (kW)", f"{tag}: Discharge Max (kW)",
             0.0, self.discharge_capacity(), self.sizing_dis),
            ("incl_ts_energy_limits", ene,
             f"{tag}: Energy Min (kWh)", f"{tag}: Energy Max (kWh)",
             e_min, e_max, self.sizing_ene),
        ]
        for key, ref, lo_col, hi_col, lo_def, hi_def, sizing in spec:
            if not self.keys.get(key, False):
                continue
            if sizing:
                TellUser.error(f"{self.name}: ignoring {key} time series "
                               "because the rating is being sized "
                               "(reference behavior)")
                continue
            lo = ctx.col(lo_col, self.id)
            hi = ctx.col(hi_col, self.id)
            if lo is None and hi is None:
                # the reference records a fatal input error here
                # (DERVETParams.load_ts_limits, :655-659)
                raise ParameterError(
                    f"{self.name}: {key} is set but neither {lo_col!r} nor "
                    f"{hi_col!r} is in the time series")
            lo_arr = np.clip(np.nan_to_num(lo, nan=lo_def), lo_def, None) \
                if lo is not None else lo_def
            hi_arr = np.clip(np.nan_to_num(hi, nan=hi_def), None, hi_def) \
                if hi is not None else hi_def
            b.set_bounds(ref, lb=lo_arr, ub=hi_arr)
            # echo the applied limits into the output timeseries
            # (reference ESSSizing.timeseries_report, :299-308:
            # '<TAG>: <name> User Charge Max (kW)' etc.)
            qty, unit = lo_col.split(": ")[1].rsplit(" ", 2)[0], \
                ("(kWh)" if "Energy" in lo_col else "(kW)")
            for stem, arr in ((f"User {qty} Max {unit}", hi_arr),
                              (f"User {qty} Min {unit}", lo_arr)):
                full = np.broadcast_to(np.asarray(arr, float), (ctx.T,))
                self._ts_user_limits.setdefault(stem, {})[ctx.label] = \
                    pd.Series(full, index=ctx.index)

    def _binary_onoff_rows(self, b: LPBuilder, ctx: WindowContext,
                           ch, dis) -> None:
        """Binary on/off formulation (scenario ``binary=1``): per-step
        charge/discharge indicator variables enforce mutual exclusion and
        the ch/dis minimum ratings (reference: storagevet EnergyStorage
        ``on_c``/``on_d`` boolean variables behind CVXPY+GLPK_MI; the LP
        IR marks the blocks integral and the scenario routes the window
        to the exact CPU MILP backend)."""
        T = ctx.T
        on_c = b.var(self.vname("on_c"), T, binary=True)
        on_d = b.var(self.vname("on_d"), T, binary=True)
        # ch <= ch_max*on_c  ->  ch_max*on_c - ch >= 0
        b.add_rows(self.vname("bin_ch_cap"),
                   [(on_c, self.charge_capacity()), (ch, -1.0)], "ge", 0.0)
        b.add_rows(self.vname("bin_dis_cap"),
                   [(on_d, self.discharge_capacity()), (dis, -1.0)], "ge", 0.0)
        if self.ch_min_rated:
            b.add_rows(self.vname("bin_ch_min"),
                       [(ch, 1.0), (on_c, -self.ch_min_rated)], "ge", 0.0)
        if self.dis_min_rated:
            b.add_rows(self.vname("bin_dis_min"),
                       [(dis, 1.0), (on_d, -self.dis_min_rated)], "ge", 0.0)
        # no simultaneous charge and discharge: on_c + on_d <= 1
        b.add_rows(self.vname("bin_excl"),
                   [(on_c, -1.0), (on_d, -1.0)], "ge", -1.0)
        if self.incl_startup:
            self._startup_rows(b, ctx, on_c, on_d)

    def _startup_rows(self, b: LPBuilder, ctx: WindowContext,
                      on_c, on_d) -> None:
        """Startup-cost formulation: ``start[t] >= on[t] - on[t-1]`` with
        cost ``p_start * sum(start)`` — positive cost drives each start
        indicator to exactly max(0, rising edge), so the continuous start
        block stays exact without extra integrality (reference: the
        EnergyStorage startup surface, incl_startup/p_start_ch/p_start_dis,
        ESSSizing.py:389-396).  The first step of a window is not charged
        (no prior on-state to compare against, matching the per-window
        reference objective)."""
        T = ctx.T
        if T < 2:
            return
        # row t (t=1..T-1):  start[t] - on[t] + on[t-1] >= 0
        pick = sp.eye(T, format="csr")[1:]               # selects x[1:]
        diff = pick - sp.eye(T, format="csr")[:-1]       # x[t] - x[t-1]
        for which, on, p_start in (("ch", on_c, self.p_start_ch),
                                   ("dis", on_d, self.p_start_dis)):
            if not p_start:
                continue
            start = b.var(self.vname(f"start_{which}"), T, lb=0.0, ub=1.0)
            b.add_rows(self.vname(f"startup_{which}"),
                       [(start, pick), (on, -diff)], "ge", 0.0)
            b.add_cost(start, p_start * ctx.annuity_scalar,
                       label=f"{self.name} startup")

    def _daily_sum_matrix(self, ctx: WindowContext) -> sp.csr_matrix:
        """(n_days, T) matrix summing dis*dt per calendar day.

        ``pd.factorize`` labels each step with its day-of-appearance in one
        vectorized pass — the per-day ``days == d`` mask loop it replaces
        cost ~60 pandas comparisons per window, the single hottest line of
        the 128-case sensitivity fan-out's host assembly (VERDICT r5 #1)."""
        codes, uniq = pd.factorize(ctx.index.normalize())
        return sp.coo_matrix(
            (np.full(ctx.T, ctx.dt), (codes, np.arange(ctx.T))),
            shape=(len(uniq), ctx.T)).tocsr()

    def _daily_cycle_rows(self, b: LPBuilder, ctx: WindowContext, dis: VarRef):
        """sum_day(dis)*dt <= daily_cycle_limit * usable energy, per day.

        Kept as per-day aggregation rows ON PURPOSE: these ride BandedOp's
        low-rank wide-row pair (two small MXU matmuls inside the fused
        kernel).  A banded-recurrence reformulation (cumulative variable
        with the cap as its bound) was measured r5 and LOST ~1.8x — it
        adds T variables + T rows of state to every HBM-bound restart
        check and costs ~15% more PDHG iterations (the daily cap signal
        propagates one chain step per iteration)."""
        mat = self._daily_sum_matrix(ctx)
        cap = self.daily_cycle_limit * (self.operational_max_energy()
                                        - self.operational_min_energy())
        b.add_rows(self.vname("daily_cycle"), [(dis, mat)], "le",
                   np.full(mat.shape[0], cap))

    # ---------------- POI interface -------------------------------------
    def power_terms(self, b: LPBuilder) -> List[Tuple[VarRef, float]]:
        return [(b[self.vname("dis")], +1.0), (b[self.vname("ch")], -1.0)]

    def fixed_load(self, ctx: WindowContext) -> Optional[np.ndarray]:
        if self.hp:
            return np.full(ctx.T, self.hp)
        return None

    def soe_term(self, b: LPBuilder) -> Optional[VarRef]:
        return b[self.vname("ene")]

    def market_headroom(self, b: LPBuilder, direction: str):
        """Up: raise discharge to rated + cut charge to zero; down: raise
        charge to rated + cut discharge (reference: storagevet EnergyStorage
        get_discharge/charge_up/down_schedule surface).  While a rating is
        being sized, its size variable supplies the nameplate term."""
        ch, dis = b[self.vname("ch")], b[self.vname("dis")]
        if direction == "up":
            terms, const = [(dis, -1.0), (ch, 1.0)], self.discharge_capacity()
            if self.sizing_dis and b.has(self.vname("size_dis")):
                terms.append((b[self.vname("size_dis")], 1.0))
                const = 0.0
            return terms, const
        terms, const = [(ch, -1.0), (dis, 1.0)], self.charge_capacity()
        if self.sizing_ch:
            # shared power sizing registers a single 'size_dis' variable
            # (reference ties ch==dis when both are zero)
            for cand in ("size_ch", "size_dis"):
                if b.has(self.vname(cand)):
                    terms.append((b[self.vname(cand)], 1.0))
                    const = 0.0
                    break
        return terms, const

    def load_series(self):
        if self.hp and self.variables_df is not None:
            return np.full(len(self.variables_df), self.hp)
        return None

    # ---------------- results -------------------------------------------
    def store_dispatch(self, index, values):
        super().store_dispatch(index, values)
        # SOE hand-off: next run starts from final energy (within a run the
        # windows pin to ene_target; carry is for degradation-coupled reruns)

    def timeseries_report(self) -> pd.DataFrame:
        v = self.variables_df
        out = pd.DataFrame(index=v.index)
        e_max = self.operational_max_energy()
        out[self.col("Charge (kW)")] = v["ch"]
        out[self.col("Discharge (kW)")] = v["dis"]
        out[self.col("Power (kW)")] = v["dis"] - v["ch"]
        out[self.col("State of Energy (kWh)")] = v["ene"]
        out[self.col("SOC (%)")] = v["ene"] / (e_max if e_max else 1.0)
        for stem, per_window in self._ts_user_limits.items():
            ser = pd.concat(per_window.values()).sort_index()
            out[self.col(stem)] = ser.reindex(out.index)
        return out

    def get_capex(self) -> float:
        return (self.ccost + self.ccost_kw * self.discharge_capacity()
                + self.ccost_kwh * self.energy_capacity())

    def replacement_cost(self) -> float:
        """rcost + rcost_kW*dis + rcost_kWh*ene (reference:
        ESSSizing.py:438-444)."""
        g = lambda k: float(self.keys.get(k, 0) or 0)
        return (g("rcost") + g("rcost_kW") * self.discharge_capacity()
                + g("rcost_kWh") * self.energy_capacity())

    def proforma_report(self, opt_years, apply_inflation_rate_func=None,
                        fill_forward_func=None):
        """Fixed + variable O&M rows per optimized year (reference:
        storagevet EnergyStorage proforma surface, SURVEY.md §2.8)."""
        uid = self.unique_tech_id
        rows = {}
        v = self.variables_df
        for yr in opt_years:
            per = pd.Period(yr, freq="Y")
            fixed = -self.fixed_om_per_kw * self.discharge_capacity()
            var = 0.0
            if v is not None and "dis" in v:
                mask = v.index.year == yr
                var = -self.variable_om * self.dt * float(v.loc[mask, "dis"].sum())
            rows[per] = {f"{uid} Fixed O&M Cost": fixed,
                         f"{uid} Variable O&M Cost": var}
        return pd.DataFrame(rows).T

    def sizing_summary(self) -> Dict:
        dis = self.discharge_capacity()
        return {
            "DER": self.name,
            "Energy Rating (kWh)": self.energy_capacity(),
            "Charge Rating (kW)": self.charge_capacity(),
            "Discharge Rating (kW)": dis,
            "Round Trip Efficiency (%)": self.rte,
            "Lower Limit on SOC (%)": self.llsoc,
            "Upper Limit on SOC (%)": self.ulsoc,
            "Duration (hours)": (self.energy_capacity() / dis) if dis else 0,
            "Capital Cost ($)": self.ccost,
            "Capital Cost ($/kW)": self.ccost_kw,
            "Capital Cost ($/kWh)": self.ccost_kwh,
        }


class Battery(EnergyStorage):
    """Battery ESS (reference: dervet/MicrogridDER/Battery.py:66-110 adds a
    duration_max sizing constraint + cycle-degradation module on top of the
    storagevet battery)."""

    def __init__(self, keys: Dict, scenario: Dict, der_id: str = "",
                 cycle_life: Optional[pd.DataFrame] = None):
        super().__init__("Battery", der_id, keys, scenario)
        self.incl_cycle_degrade = bool(keys.get("incl_cycle_degrade", False))
        self.cycle_life = cycle_life
        g = lambda k, d=0.0: float(keys.get(k, d) or 0.0)
        self.yearly_degrade = g("yearly_degrade") / 100.0
        self.state_of_health = g("state_of_health") / 100.0
        # replaceable comes from the base lifecycle property (keys)
        self.degrade_perc = 0.0
        self.years_system_degraded: set = set()
        self.degradation_log: List[Dict] = []
        self._damage_model = None
        if self.incl_cycle_degrade:
            if cycle_life is None:
                raise ParameterError(
                    f"{self.name}: incl_cycle_degrade requires a "
                    "cycle_life_filename")
            from .degradation import CycleDegradation
            self._damage_model = CycleDegradation(cycle_life)
        if self.duration_max and self.dis_max_rated:
            if self.ene_max_rated > self.duration_max * self.dis_max_rated:
                raise ParameterError(
                    f"{self.name}: energy rating {self.ene_max_rated} exceeds "
                    f"duration_max*discharge rating "
                    f"{self.duration_max * self.dis_max_rated}")

    # ---------------- degradation lifecycle ----------------------------
    # (reference: Battery.py:69-110 calc_degradation + replacement reset;
    # the rainflow damage model itself lives in degradation.py)
    def degraded_energy_capacity(self) -> float:
        return (1.0 - self.degrade_perc) * self.energy_capacity()

    def calc_degradation(self, window_index: pd.DatetimeIndex,
                         soe: np.ndarray) -> None:
        """Update SOH after one solved window from its SOE profile."""
        if not self.incl_cycle_degrade:
            return
        cap = self.energy_capacity()
        if cap <= 0:
            return
        cycle = self._damage_model.damage(np.asarray(soe) / cap)
        hours = len(window_index) * self.dt
        calendar = self.yearly_degrade * hours / 8760.0
        self.degrade_perc += cycle + calendar
        year = int(window_index[0].year)
        replaced = False
        if self.degraded_energy_capacity() <= cap * self.state_of_health:
            self.years_system_degraded.add(year)
            if self.replaceable:
                self.degrade_perc = 0.0
                replaced = True
                TellUser.info(f"{self.name}: replaced in {year} (SOH hit "
                              f"{self.state_of_health:.0%})")
            else:
                TellUser.warning(f"{self.name}: reached end of life in "
                                 f"{year} and is not replaceable")
        self.soh = 1.0 - self.degrade_perc
        self.degradation_log.append({
            "Window Start": window_index[0], "Cycle Degradation": cycle,
            "Calendar Degradation": calendar,
            "State of Health (%)": self.soh * 100.0, "Replaced": replaced})

    def degradation_report(self) -> Optional[pd.DataFrame]:
        if not self.degradation_log:
            return None
        return pd.DataFrame(self.degradation_log).set_index("Window Start")

    def estimated_lifetime_years(self) -> Optional[float]:
        """Years until SOH hits the replacement threshold at the observed
        average degradation rate (reference:
        set_end_of_life_based_on_degradation_cycle, Battery.py:112-179)."""
        if not self.degradation_log:
            return None
        df = pd.DataFrame(self.degradation_log)
        total = df["Cycle Degradation"].sum() + df["Calendar Degradation"].sum()
        spans = df["Window Start"]
        span_years = 1.0
        if len(spans) >= 2:
            span_years = max((spans.iloc[-1] - spans.iloc[0]).days / 365.25,
                             1.0 / 12.0)
        rate = total / span_years
        if rate <= 0:
            return None
        return (1.0 - self.state_of_health) / rate
