"""Reliability (islanding/resilience) value stream.

Re-implements dervet/MicrogridValueStreams/Reliability.py (SURVEY.md §2.5)
TPU-first.  The reference simulates an outage starting at EVERY timestep
with a recursive per-step Python walk (`simulate_outage`,
Reliability.py:489-570, called in a while loop at :876-966 — its own log
says "This may take a while").  Here the same greedy SOE walk is a
``jax.lax.scan`` over outage steps ``vmap``-ed over all start indices: one
compiled kernel evaluates all T x L cells at once on TPU/CPU.

Numeric semantics preserved from the reference:
* ``data_process`` rounding to 5 decimals (Reliability.py:466-470)
* the 2-decimal feasibility checks inside the walk (:548,:554)
* rolling-forward energy requirement (:120-122, :356-373)
* LCPC probability accounting incl. end-of-horizon truncation (:915-955)
* min-SOE schedule = per-start effective SOE swing of a target-length
  outage from the initial SOC (:685-732) -> 'energy'/'min' requirement

Documented divergence: the reference draws a RANDOM round-trip efficiency
per charge step from the ESS rte list (:532 ``random.choice``); we use the
worst (lowest) rte deterministically — reproducible and conservative.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import scipy.sparse as sp

from ...ops.lp import LPBuilder
from ...scenario.window import WindowContext, grab_column
from ...utils.errors import TellUser, TimeseriesDataError
from .base import SystemRequirement, ValueStream

CRIT_COL = "Critical Load (kW)"


def rolling_forward_sum(arr: np.ndarray, window: int) -> np.ndarray:
    """Sum of the next ``window`` values at each index (fewer at the end) —
    reference ``rolling_sum`` on the reversed series (Reliability.py:356-373).
    """
    s = pd.Series(arr[::-1]).rolling(window, min_periods=1).sum()
    return s.to_numpy()[::-1]


# ---------------------------------------------------------------------------
# vectorized outage walk
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("L",))
def _simulate_all_outages(crit: jax.Array, gen: jax.Array, pv_max: jax.Array,
                          pv_vari: jax.Array, gamma: float, shed: jax.Array,
                          init_soe: jax.Array,
                          ch_max: float, dis_max: float, e_min: float,
                          e_max: float, rte: float, dt: float, L: int):
    """Greedy SOE walk for an outage starting at every timestep.

    Inputs are full-horizon (T,) arrays plus a per-OUTAGE-STEP load-shed
    factor ``shed`` of length L (fraction of critical load that must be
    served at outage hour j — reference data_process applies the shed
    curve by outage step, Reliability.py:471-485).  Returns ``(coverage,
    profiles)`` where ``coverage[i]`` counts survived steps (capped by
    horizon end) and ``profiles[i, j]`` is the SOE after step j of the
    outage starting at i (0 once dead).  Mirrors Reliability.py:489-570
    incl. the 5-decimal data rounding and 2-decimal feasibility checks.
    """
    T = crit.shape[0]
    starts = jnp.arange(T)

    def _round5(x):
        return jnp.round(x * 1e5) / 1e5

    def step(carry, j):
        soe, alive = carry
        idx = starts + j
        in_range = idx < T
        idxc = jnp.minimum(idx, T - 1)
        load = crit[idxc] * shed[j]
        rc = _round5(load - gen[idxc] - pv_vari[idxc])
        dl = _round5(load - gen[idxc] - pv_max[idxc])
        ec = rc * gamma

        # surplus branch: generation covers the load; charge what fits
        can_store = e_max >= soe
        charge_possible = (e_max - soe) / (rte * dt)
        charge = jnp.minimum(jnp.minimum(charge_possible, -dl), ch_max)
        charge = jnp.maximum(charge, 0.0)
        soe_surplus = jnp.where(can_store, soe + charge * rte * dt, soe)

        # deficit branch: need the ESS; check energy then discharge
        enough_energy = jnp.round((ec * dt - soe) * 100.0) / 100.0 <= 0.0
        discharge_possible = (soe - e_min) / dt
        discharge = jnp.minimum(jnp.minimum(discharge_possible, dl), dis_max)
        met = jnp.round((dl - discharge) * 100.0) / 100.0 <= 0.0
        soe_deficit = soe - discharge * dt
        deficit_ok = enough_energy & met

        surplus = rc <= 0.0
        survives = alive & in_range & (surplus | deficit_ok)
        new_soe = jnp.where(surplus, soe_surplus, soe_deficit)
        new_soe = jnp.where(survives, new_soe, soe)
        return (new_soe, survives), (survives, new_soe)

    (_, _), (alive_steps, profiles) = jax.lax.scan(
        step, (init_soe, jnp.ones(T, bool)), jnp.arange(L))
    coverage = jnp.sum(alive_steps, axis=0)
    profiles = jnp.where(alive_steps, profiles, 0.0)
    return coverage, jnp.transpose(profiles)


def _min_soe_required(crit: jax.Array, gen: jax.Array, pv_max: jax.Array,
                      pv_vari: jax.Array, gamma: float, shed: jax.Array,
                      ch_max: float, dis_max: float, e_min: float,
                      e_max: float, rte: float, dt: float, L: int):
    """EXACT minimal initial SOE per outage start (vmapped backward
    recursion).

    TPU-native equivalent of the reference's exact ``min_soe_opt``
    (Reliability.py:572-683): that MILP is separable per outage start —
    each start's sub-problem shares no variables with the others — and for
    the aggregate single-state ESS model the per-start optimum has a
    closed-form backward recursion: walking outage steps last-to-first,
    ``m[j]`` is the least SOE at step j from which steps j..L-1 are
    survivable.  Deficit steps must discharge the full net load (so
    ``m[j] = max(e_min + dl*dt, ec*dt, m[j+1] + dl*dt)``, infeasible when
    ``dl`` exceeds the discharge rating); surplus steps may charge up to
    ``min(-dl, ch_max)`` (so ``m[j] = max(e_min, m[j+1] - charge)``,
    infeasible when ``m[j+1]`` exceeds the energy cap).  One
    ``lax.scan`` over L steps evaluates every start simultaneously —
    replacing T_month x one-LP-per-start MILPs with L fused vector steps.
    Data rounding matches the forward walk (5 decimals); the walk's
    2-decimal feasibility slack is granted on the discharge-rating check,
    and the remaining thresholds are exact — i.e. the schedule is
    conservative relative to the simulator by at most 0.005 kW/kWh per
    step, never optimistic.
    """
    T = crit.shape[0]
    starts = jnp.arange(T)

    def _round5(x):
        return jnp.round(x * 1e5) / 1e5

    def step(m_next, j):
        idx = starts + j
        in_range = idx < T
        idxc = jnp.minimum(idx, T - 1)
        load = crit[idxc] * shed[j]
        rc = _round5(load - gen[idxc] - pv_vari[idxc])
        dl = _round5(load - gen[idxc] - pv_max[idxc])
        ec = rc * gamma
        # deficit: the ESS must discharge the full net load dl.  The
        # forward walk accepts a shortfall that rounds to zero at two
        # decimals (met/enough_energy checks) — grant the same 0.005
        # slack here so borderline starts the simulation survives are not
        # declared uncoverable (the recursion stays conservative by at
        # most that slack per step elsewhere)
        feas = dl <= dis_max + 5e-3
        m_deficit = jnp.maximum(jnp.maximum(e_min + dl * dt, ec * dt),
                                m_next + dl * dt)
        m_deficit = jnp.where(feas, m_deficit, jnp.inf)
        # surplus: optional charging helps reach the NEXT requirement
        chg = jnp.maximum(jnp.minimum(-dl, ch_max), 0.0) * rte * dt
        m_surplus = jnp.maximum(e_min, m_next - chg)
        m_surplus = jnp.where(m_next <= e_max + 1e-9, m_surplus, jnp.inf)
        m = jnp.where(rc <= 0.0, m_surplus, m_deficit)
        # outage truncated at the horizon end: no requirement beyond it
        m = jnp.where(in_range, m, e_min)
        return m, None

    m0, _ = jax.lax.scan(step, jnp.full(T, float(e_min)),
                         jnp.arange(L - 1, -1, -1))
    return m0


class Reliability(ValueStream):
    """Microgrid islanding reliability (dervet Reliability tag)."""

    def __init__(self, keys, scenario, datasets, load_shed_data=None):
        super().__init__("Reliability", keys, scenario, datasets)
        g = lambda k, d=0.0: float(keys.get(k, d) or 0.0)
        self.outage_duration = g("target")            # hours to cover
        self.dt = float(scenario.get("dt", 1))
        self.post_facto_only = bool(keys.get("post_facto_only", False))
        self.soc_init = g("post_facto_initial_soc", 100.0) / 100.0
        self.max_outage_duration = g("max_outage_duration",
                                     self.outage_duration or 1)
        self.n_2 = bool(keys.get("n-2", False))
        # exact per-start minimal-SOE schedule (the reference's min_soe_opt
        # exact mode, Reliability.py:572-683 — commented out of its own
        # default path at :215-217); opt-in extension key, default keeps
        # the reference's default iterative method
        self.min_soe_exact = bool(keys.get("min_soe_exact", False))
        self.load_shed = bool(keys.get("load_shed_percentage", False))
        self.load_shed_data: Optional[np.ndarray] = None
        if self.load_shed:
            if load_shed_data is None:
                load_shed_data = getattr(datasets, "load_shed", None)
            if load_shed_data is None:
                raise TimeseriesDataError(
                    "load_shed_percentage requires load_shed_perc_filename")
            col = [c for c in load_shed_data.columns
                   if "load shed" in c.lower()]
            self.load_shed_data = load_shed_data[col[0]].to_numpy(np.float64)
        ts = datasets.time_series
        if ts is None or grab_column(ts, CRIT_COL) is None:
            raise TimeseriesDataError(
                f"Reliability requires a {CRIT_COL!r} column")
        self.critical_load: Optional[pd.Series] = None
        self.requirement: Optional[np.ndarray] = None
        self.min_soe_df: Optional[pd.DataFrame] = None
        self.soe_profiles: Optional[pd.DataFrame] = None
        self.outage_contribution_df: Optional[pd.DataFrame] = None
        self.outage_soe_profile: Optional[pd.DataFrame] = None
        self.dg_rating = 0.0                          # n-2 reserve margin
        self.use_sizing_module_results = False

    # ------------------------------------------------------------------
    def _prepare(self, index: pd.DatetimeIndex) -> None:
        ts = self.datasets.time_series.loc[index]
        self.critical_load = pd.Series(grab_column(ts, CRIT_COL), index=index)
        cov = int(np.round(self.outage_duration / self.dt)) or 1
        self.coverage_steps = cov
        self.requirement = rolling_forward_sum(
            self.critical_load.to_numpy(), cov) * self.dt

    # ------------------------------------------------------------------
    def _der_mix(self, ders) -> Dict:
        """Aggregate DER properties for the outage walk (reference
        ``get_der_mix_properties``, Reliability.py:276-332)."""
        props = {"charge max": 0.0, "discharge max": 0.0, "soe min": 0.0,
                 "soe max": 0.0, "energy rating": 0.0, "rte": 1.0,
                 "rte list": []}
        T = len(self.critical_load)
        pv_max = np.zeros(T)
        pv_vari = np.zeros(T)
        largest_gamma = 0.0
        dg_max = 0.0
        for d in ders:
            ttype = d.technology_type
            if ttype == "Intermittent Resource":
                gen = d.maximum_generation_series(self.critical_load.index)
                pv_max += gen
                pv_vari += gen * getattr(d, "nu", 1.0)
                largest_gamma = max(largest_gamma, getattr(d, "gamma", 1.0))
            elif ttype == "Generator":
                rating = getattr(d, "max_power_out", 0.0)
                dg_max += rating
                # n-2: hold the LARGEST single unit out of the walk
                # (reference Reliability.py:328-330 dg_rating margin)
                self.dg_rating = max(self.dg_rating, rating)
            elif ttype == "Energy Storage System":
                props["rte list"].append(d.rte)
                props["soe min"] += d.operational_min_energy()
                props["soe max"] += d.operational_max_energy()
                props["charge max"] += d.charge_capacity()
                props["discharge max"] += d.discharge_capacity()
                props["energy rating"] += d.energy_capacity()
        if self.n_2:
            dg_max -= self.dg_rating
        if props["rte list"]:
            # deterministic worst-rte (divergence from random.choice, see
            # module docstring)
            props["rte"] = float(min(props["rte list"]))
        gen = np.full(T, dg_max)
        return {"props": props, "gen": gen, "pv_max": pv_max,
                "pv_vari": pv_vari, "gamma": largest_gamma}

    def _shed_curve(self, L: int) -> np.ndarray:
        """Per-outage-step fraction of critical load to serve (reference:
        load_shed_data applies by outage step, Reliability.py:471-485)."""
        shed = np.ones(L)
        if self.load_shed and self.load_shed_data is not None:
            k = min(L, len(self.load_shed_data))
            shed[:k] = self.load_shed_data[:k] / 100.0
            if k < L:
                shed[k:] = self.load_shed_data[-1] / 100.0
        return shed

    def _walk(self, mix, init_soe: np.ndarray, L: int):
        p = mix["props"]
        cov, prof = _simulate_all_outages(
            jnp.asarray(self.critical_load.to_numpy()),
            jnp.asarray(mix["gen"]), jnp.asarray(mix["pv_max"]),
            jnp.asarray(mix["pv_vari"]), mix["gamma"],
            jnp.asarray(self._shed_curve(L)),
            jnp.asarray(init_soe, jnp.float64 if jax.config.jax_enable_x64
                        else jnp.float32),
            p["charge max"], p["discharge max"], p["soe min"], p["soe max"],
            p["rte"], self.dt, L)
        return np.asarray(cov), np.asarray(prof)

    # ------------------------------------------------------------------
    # reliability-driven sizing (reference Reliability.py:153-274):
    # iterate {min-capex LP covering candidate outages} -> {vectorized
    # walk to find the first uncovered start} until everything is covered.
    # The reference's GLPK_MI integer sizing relaxes to a continuous LP
    # (SURVEY §7); its recursive 500-at-a-time uncovered search becomes
    # one vmapped walk over every start.
    # ------------------------------------------------------------------
    def sizing_module(self, ders, index: pd.DatetimeIndex,
                      max_rounds: int = 40):
        self._prepare(index)
        from ...ops import cpu_ref
        T = len(index)
        L = self.coverage_steps
        candidates = [int(i) for i in np.argsort(-self.requirement)[:10]]
        sizes = {}
        for round_no in range(max_rounds):
            sizes = self._size_for_outages(ders, index, candidates)
            self._apply_sizes(ders, sizes, freeze=False)
            mix = self._der_mix(ders)
            p = mix["props"]
            init = np.full(T, self.soc_init * p["energy rating"])
            cov, _ = self._walk(mix, init, L)
            cov = np.minimum(cov, T - np.arange(T))
            uncovered = np.nonzero((cov < L) & (cov < (T - np.arange(T))))[0]
            if not len(uncovered):
                TellUser.info(f"reliability sizing converged after "
                              f"{round_no + 1} round(s): "
                              f"{ {k: round(v, 1) for k, v in sizes.items()} }")
                break
            first = int(uncovered[0])
            if first in candidates:
                TellUser.warning("reliability sizing: first uncovered outage "
                                 f"at {first} already constrained; stopping")
                break
            candidates.append(first)
        self._apply_sizes(ders, sizes, freeze=True)
        self.use_sizing_module_results = True
        self.min_soe_schedule(ders, index)
        return ders

    @staticmethod
    def _apply_sizes(ders, sizes: Dict[str, float], freeze: bool) -> None:
        """Push solved sizes onto the DERs.  During the iteration the
        ratings update but the sizing FLAGS stay on (the next round's LP
        must keep them variable); only the final call freezes via
        set_size."""
        for d in ders:
            der_sizes = {k.split("/")[-1]: v for k, v in sizes.items()
                         if k.startswith(f"{d.tag}-{d.id or '1'}/")}
            if not der_sizes:
                continue
            if freeze:
                d.set_size(der_sizes)
                continue
            if "size_ene" in der_sizes:
                d.ene_max_rated = der_sizes["size_ene"]
            if "size_dis" in der_sizes:
                d.dis_max_rated = der_sizes["size_dis"]
                if getattr(d, "sizing_ch", False):
                    d.ch_max_rated = der_sizes["size_dis"]
            if "size" in der_sizes:
                if hasattr(d, "rated_power"):
                    d.rated_power = der_sizes["size"]
                else:
                    d.rated_capacity = der_sizes["size"]

    def _size_for_outages(self, ders, index: pd.DatetimeIndex,
                          starts: List[int]) -> Dict[str, float]:
        """Min-capex LP: chosen sizes must cover every candidate outage
        window (reference size_for_outages, Reliability.py:221-274)."""
        from ...ops.lp import LPBuilder
        from ...ops import cpu_ref
        b = LPBuilder()
        T = len(index)
        L = self.coverage_steps
        dt = self.dt
        crit_full = self.critical_load.to_numpy()

        ess = [d for d in ders
               if d.technology_type == "Energy Storage System"]
        pvs = [d for d in ders if d.technology_type == "Intermittent Resource"]
        gens = [d for d in ders if d.technology_type == "Generator"]

        # ---- size variables / numeric ratings -------------------------
        size_refs: Dict[str, object] = {}
        for e in ess:
            if getattr(e, "sizing_ene", False):
                ref = b.var(e.vname("size_ene"), 1, lb=0.0)
                size_refs[e.vname("size_ene")] = ref
                b.add_cost(ref, float(e.ccost_kwh))
            if getattr(e, "sizing_ch", False) or getattr(e, "sizing_dis", False):
                ref = b.var(e.vname("size_dis"), 1, lb=0.0)
                size_refs[e.vname("size_dis")] = ref
                b.add_cost(ref, float(e.ccost_kw))
        for g in gens:
            if g.being_sized():
                ref = b.var(g.vname("size"), 1, lb=0.0)
                size_refs[g.vname("size")] = ref
                b.add_cost(ref, float(g.ccost_kw) * g.n_units)
        for pv in pvs:
            if pv.being_sized():
                ref = b.var(pv.vname("size"), 1, lb=0.0)
                size_refs[pv.vname("size")] = ref
                b.add_cost(ref, float(pv.cost_per_kw))

        # ---- per-outage coverage blocks -------------------------------
        for k, s0 in enumerate(sorted(set(int(s) for s in starts))):
            Lk = int(min(L, T - s0))
            if Lk <= 0:
                continue
            crit = crit_full[s0:s0 + Lk] * self._shed_curve(Lk)
            balance = []          # terms summing to supply (kW)
            const_supply = np.zeros(Lk)
            for e in ess:
                ch = b.var(f"o{k}/{e.vname('ch')}", Lk, lb=0.0)
                dis = b.var(f"o{k}/{e.vname('dis')}", Lk, lb=0.0)
                ene = b.var(f"o{k}/{e.vname('ene')}", Lk, lb=0.0)
                diag = sp.diags([np.ones(Lk), -np.ones(Lk - 1)],
                                offsets=[0, -1], format="csr")
                soe_terms = [(ene, diag), (ch, -e.rte * dt), (dis, dt)]
                first_col = sp.csr_matrix(
                    (np.ones(1), (np.zeros(1, int), np.zeros(1, int))),
                    shape=(Lk, 1))
                if e.vname("size_ene") in size_refs:
                    se = size_refs[e.vname("size_ene")]
                    soe_terms.append((se, first_col * (-self.soc_init)))
                    b.add_rows(f"o{k}/{e.vname('soe')}", soe_terms, "eq", 0.0)
                    b.add_rows(f"o{k}/{e.vname('ene_ub')}",
                               [(ene, 1.0), (se, -e.ulsoc * np.ones((Lk, 1)))],
                               "le", 0.0)
                else:
                    rhs = np.zeros(Lk)
                    rhs[0] = self.soc_init * e.energy_capacity()
                    b.add_rows(f"o{k}/{e.vname('soe')}", soe_terms, "eq", rhs)
                    b.set_bounds(ene, lb=e.operational_min_energy(),
                                 ub=e.operational_max_energy())
                if e.vname("size_dis") in size_refs:
                    sd = size_refs[e.vname("size_dis")]
                    b.add_rows(f"o{k}/{e.vname('ch_ub')}",
                               [(ch, 1.0), (sd, -np.ones((Lk, 1)))], "le", 0.0)
                    b.add_rows(f"o{k}/{e.vname('dis_ub')}",
                               [(dis, 1.0), (sd, -np.ones((Lk, 1)))], "le", 0.0)
                else:
                    b.set_bounds(ch, ub=e.charge_capacity())
                    b.set_bounds(dis, ub=e.discharge_capacity())
                balance.extend([(dis, np.ones(Lk)), (ch, -np.ones(Lk))])
            for g in gens:
                elec = b.var(f"o{k}/{g.vname('elec')}", Lk, lb=0.0)
                if g.vname("size") in size_refs:
                    sg = size_refs[g.vname("size")]
                    b.add_rows(f"o{k}/{g.vname('cap')}",
                               [(elec, 1.0),
                                (sg, -float(g.n_units) * np.ones((Lk, 1)))],
                               "le", 0.0)
                else:
                    b.set_bounds(elec, ub=g.max_power_out)
                balance.append((elec, np.ones(Lk)))
            for pv in pvs:
                per_kw = np.asarray(grab_column(
                    self.datasets.time_series.loc[index],
                    "PV Gen (kW/rated kW)", pv.id))[s0:s0 + Lk]
                nu = getattr(pv, "nu", 1.0)
                if pv.vname("size") in size_refs:
                    sp_ref = size_refs[pv.vname("size")]
                    balance.append((sp_ref, (nu * per_kw)[:, None]))
                else:
                    const_supply += nu * per_kw * pv.rated_capacity
            if not balance:
                raise TimeseriesDataError(
                    "reliability sizing needs at least one dispatchable DER")
            b.add_rows(f"o{k}/balance", balance, "ge", crit - const_supply)

        lp = b.build()
        res = cpu_ref.solve_lp_cpu(lp)
        if res.status != 0:
            raise TimeseriesDataError(
                "reliability sizing LP failed: "
                f"{getattr(res, 'message', 'solver failure')}")
        return {name: float(res.x[ref.sl][0])
                for name, ref in lp.var_refs.items() if name in size_refs}

    # ------------------------------------------------------------------
    # pre-dispatch: min-SOE schedule -> system requirement
    # ------------------------------------------------------------------
    def min_soe_schedule(self, ders, index: pd.DatetimeIndex) -> Optional[pd.DataFrame]:
        """Per-timestep minimum SOE so a target-length outage starting there
        is covered (reference ``min_soe_iterative``, Reliability.py:685-732:
        effective swing of the simulated profile from the initial SOC)."""
        if self.critical_load is None:
            self._prepare(index)
        mix = self._der_mix(ders)
        p = mix["props"]
        if p["energy rating"] <= 0:
            return None
        L = self.coverage_steps
        if self.min_soe_exact:
            req = np.asarray(_min_soe_required(
                jnp.asarray(self.critical_load.to_numpy()),
                jnp.asarray(mix["gen"]), jnp.asarray(mix["pv_max"]),
                jnp.asarray(mix["pv_vari"]), mix["gamma"],
                jnp.asarray(self._shed_curve(L)),
                p["charge max"], p["discharge max"], p["soe min"],
                p["soe max"], p["rte"], self.dt, L))
            n_bad = int(np.sum(req > p["soe max"] + 1e-6))
            if n_bad:
                TellUser.warning(
                    f"min_soe_exact: {n_bad} outage start(s) are not "
                    "coverable at any state of energy — requirement capped "
                    "at the fleet energy limit")
            self.min_soe_df = pd.DataFrame(
                {"soe": np.minimum(req, p["soe max"])}, index=index)
            return self.min_soe_df
        init = np.full(len(index), self.soc_init * p["energy rating"])
        cov, prof = self._walk(mix, init, L)
        # profile incl. the initial soe at the front
        full = np.concatenate([init[:, None], prof], axis=1)
        # dead steps are zero-filled; effective swing over surviving steps
        steps = np.arange(L + 1)[None, :]
        alive = steps <= np.minimum(cov, L)[:, None]
        vals = np.where(alive, full, np.nan)
        swing = np.nanmax(vals, axis=1) - np.nanmin(vals, axis=1)
        self.min_soe_df = pd.DataFrame({"soe": swing}, index=index)
        self.soe_profiles = pd.DataFrame(
            {f"Reliability min SOE profile {k}":
             (prof[:, k] if k < prof.shape[1] else np.zeros(len(index)))
             for k in range(min(L, 2))}, index=index)
        return self.min_soe_df

    def system_requirements(self, ders, years, index) -> List[SystemRequirement]:
        if self.post_facto_only:
            return []
        self._prepare(index)
        self.min_soe_schedule(ders, index)
        if self.min_soe_df is None:
            return []
        return [SystemRequirement("energy", "min", "Reliability",
                                  self.min_soe_df["soe"])]

    # ------------------------------------------------------------------
    # results
    # ------------------------------------------------------------------
    def timeseries_report(self, index) -> pd.DataFrame:
        if self.critical_load is None:
            self._prepare(index)
        out = pd.DataFrame(index=index)
        if not self.post_facto_only:
            out["Total Critical Load (kWh)"] = self.requirement
        out[CRIT_COL] = self.critical_load
        if self.min_soe_df is not None:
            out["Reliability min State of Energy (kWh)"] = self.min_soe_df["soe"]
            if self.soe_profiles is not None:
                for c in self.soe_profiles.columns:
                    out[c] = self.soe_profiles[c]
        return out

    def load_coverage_probability(self, ders, results: pd.DataFrame
                                  ) -> pd.DataFrame:
        """LCPC: simulate an outage at every timestep; P(cover len) =
        fraction of feasible starts that survive >= len (reference
        Reliability.py:876-966 incl. end-truncation accounting)."""
        index = results.index
        if self.critical_load is None:
            self._prepare(index)
        mix = self._der_mix(ders)
        p = mix["props"]
        T = len(index)
        L = int(np.round(self.max_outage_duration / self.dt))
        if p["energy rating"] > 0:
            if self.use_sizing_module_results and self.min_soe_df is not None \
                    and "Aggregated State of Energy (kWh)" not in results:
                # no dispatch ran: start each outage from the min-SOE
                # schedule (reference Reliability.py:905-911)
                init = self.min_soe_df["soe"].to_numpy()
            elif "Aggregated State of Energy (kWh)" in results and \
                    not self.post_facto_only:
                init = results["Aggregated State of Energy (kWh)"].to_numpy()
            else:
                init = np.full(T, self.soc_init * p["energy rating"])
        else:
            init = np.zeros(T)
        cov, prof = self._walk(mix, init, L)
        # cap coverage at steps remaining in the horizon
        cov = np.minimum(cov, T - np.arange(T))
        freq = np.bincount(cov.astype(int), minlength=L + 1)
        probs = []
        lengths = np.arange(1, L + 1)
        for k in lengths:
            covered = freq[k:].sum()
            possible = T - k + 1
            probs.append(covered / possible)
        self.outage_soe_profile = pd.DataFrame(
            {h: prof[:, h - 1] for h in range(1, L + 1)}, index=index)
        return pd.DataFrame({
            "Outage Length (hrs)": lengths * self.dt,
            "Load Coverage Probability (%)": probs,
        }).set_index("Outage Length (hrs)")

    def contribution_summary(self, ders, results: pd.DataFrame
                             ) -> pd.DataFrame:
        """Split the outage energy requirement across PV -> storage -> fuel
        (reference Reliability.py:806-874 waterfall order)."""
        index = results.index
        outage_energy = pd.Series(self.requirement, index=index)
        cols = {}
        pv = [d for d in ders if d.technology_type == "Intermittent Resource"]
        if pv:
            agg = np.zeros(len(index))
            for d in pv:
                agg += d.maximum_generation_series(index)
            pv_e = pd.Series(rolling_forward_sum(agg, self.coverage_steps)
                             * self.dt, index=index)
            net = outage_energy - pv_e
            outage_energy = net.clip(lower=0)
            pv_e = pv_e + net.clip(upper=0)
            cols["PV Outage Contribution (kWh)"] = pv_e
        ess = [d for d in ders if d.technology_type == "Energy Storage System"]
        if ess:
            if "Aggregated State of Energy (kWh)" in results:
                soe = results["Aggregated State of Energy (kWh)"]
            else:
                soe = pd.Series(0.0, index=index)
            net = outage_energy - soe
            outage_energy = net.clip(lower=0)
            cols["Storage Outage Contribution (kWh)"] = soe + net.clip(upper=0)
        gens = [d for d in ders if d.technology_type == "Generator"]
        if gens:
            cols["ICE Outage Contribution (kWh)"] = outage_energy
        self.outage_contribution_df = pd.DataFrame(cols, index=index)
        return self.outage_contribution_df

    def drill_down_dfs(self, results: pd.DataFrame, dt: float
                       ) -> Dict[str, pd.DataFrame]:
        return {}  # populated via drill_down_reports (needs the DER list)

    def drill_down_reports(self, ders, results: pd.DataFrame
                           ) -> Dict[str, pd.DataFrame]:
        TellUser.info("Starting load coverage calculation...")
        out = {"load_coverage_prob": self.load_coverage_probability(ders, results)}
        out["lcp_outage_soe_profiles"] = self.outage_soe_profile
        if not self.post_facto_only:
            out["outage_energy_contributions"] = \
                self.contribution_summary(ders, results)
        TellUser.info("Finished load coverage calculation.")
        return out
