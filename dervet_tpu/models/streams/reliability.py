"""Reliability (islanding/resilience) value stream.

Re-implements dervet/MicrogridValueStreams/Reliability.py (SURVEY.md §2.5)
TPU-first.  The reference simulates an outage starting at EVERY timestep
with a recursive per-step Python walk (`simulate_outage`,
Reliability.py:489-570, called in a while loop at :876-966 — its own log
says "This may take a while").  Here the same greedy SOE walk is a
``jax.lax.scan`` over outage steps ``vmap``-ed over all start indices: one
compiled kernel evaluates all T x L cells at once on TPU/CPU.

Numeric semantics preserved from the reference:
* ``data_process`` rounding to 5 decimals (Reliability.py:466-470)
* the 2-decimal feasibility checks inside the walk (:548,:554)
* rolling-forward energy requirement (:120-122, :356-373)
* LCPC probability accounting incl. end-of-horizon truncation (:915-955)
* min-SOE schedule = per-start effective SOE swing of a target-length
  outage from the initial SOC (:685-732) -> 'energy'/'min' requirement

Documented divergence: the reference draws a RANDOM round-trip efficiency
per charge step from the ESS rte list (:532 ``random.choice``); we use the
worst (lowest) rte deterministically — reproducible and conservative.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from ...ops.lp import LPBuilder
from ...scenario.window import WindowContext, grab_column
from ...utils.errors import TellUser, TimeseriesDataError
from .base import SystemRequirement, ValueStream

CRIT_COL = "Critical Load (kW)"


def rolling_forward_sum(arr: np.ndarray, window: int) -> np.ndarray:
    """Sum of the next ``window`` values at each index (fewer at the end) —
    reference ``rolling_sum`` on the reversed series (Reliability.py:356-373).
    """
    s = pd.Series(arr[::-1]).rolling(window, min_periods=1).sum()
    return s.to_numpy()[::-1]


# ---------------------------------------------------------------------------
# vectorized outage walk
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("L",))
def _simulate_all_outages(reliability_check: jax.Array, demand_left: jax.Array,
                          energy_check: jax.Array, init_soe: jax.Array,
                          ch_max: float, dis_max: float, e_min: float,
                          e_max: float, rte: float, dt: float, L: int):
    """Greedy SOE walk for an outage starting at every timestep.

    Inputs are full-horizon (T,) arrays; returns ``(coverage, profiles)``
    where ``coverage[i]`` counts survived steps (capped by horizon end) and
    ``profiles[i, j]`` is the SOE after step j of the outage starting at i
    (0 once dead).  Mirrors reference Reliability.py:489-570.
    """
    T = reliability_check.shape[0]
    starts = jnp.arange(T)

    def step(carry, j):
        soe, alive = carry
        idx = starts + j
        in_range = idx < T
        idxc = jnp.minimum(idx, T - 1)
        rc = reliability_check[idxc]
        dl = demand_left[idxc]
        ec = energy_check[idxc]

        # surplus branch: generation covers the load; charge what fits
        can_store = e_max >= soe
        charge_possible = (e_max - soe) / (rte * dt)
        charge = jnp.minimum(jnp.minimum(charge_possible, -dl), ch_max)
        charge = jnp.maximum(charge, 0.0)
        soe_surplus = jnp.where(can_store, soe + charge * rte * dt, soe)

        # deficit branch: need the ESS; check energy then discharge
        enough_energy = jnp.round((ec * dt - soe) * 100.0) / 100.0 <= 0.0
        discharge_possible = (soe - e_min) / dt
        discharge = jnp.minimum(jnp.minimum(discharge_possible, dl), dis_max)
        met = jnp.round((dl - discharge) * 100.0) / 100.0 <= 0.0
        soe_deficit = soe - discharge * dt
        deficit_ok = enough_energy & met

        surplus = rc <= 0.0
        survives = alive & in_range & (surplus | deficit_ok)
        new_soe = jnp.where(surplus, soe_surplus, soe_deficit)
        new_soe = jnp.where(survives, new_soe, soe)
        return (new_soe, survives), (survives, new_soe)

    (_, _), (alive_steps, profiles) = jax.lax.scan(
        step, (init_soe, jnp.ones(T, bool)), jnp.arange(L))
    coverage = jnp.sum(alive_steps, axis=0)
    profiles = jnp.where(alive_steps, profiles, 0.0)
    return coverage, jnp.transpose(profiles)


class Reliability(ValueStream):
    """Microgrid islanding reliability (dervet Reliability tag)."""

    def __init__(self, keys, scenario, datasets, load_shed_data=None):
        super().__init__("Reliability", keys, scenario, datasets)
        g = lambda k, d=0.0: float(keys.get(k, d) or 0.0)
        self.outage_duration = g("target")            # hours to cover
        self.dt = float(scenario.get("dt", 1))
        self.post_facto_only = bool(keys.get("post_facto_only", False))
        self.soc_init = g("post_facto_initial_soc", 100.0) / 100.0
        self.max_outage_duration = g("max_outage_duration",
                                     self.outage_duration or 1)
        self.n_2 = bool(keys.get("n-2", False))
        self.load_shed = bool(keys.get("load_shed_percentage", False))
        self.load_shed_data: Optional[np.ndarray] = None
        if self.load_shed:
            if load_shed_data is None:
                load_shed_data = getattr(datasets, "load_shed", None)
            if load_shed_data is None:
                raise TimeseriesDataError(
                    "load_shed_percentage requires load_shed_perc_filename")
            col = [c for c in load_shed_data.columns
                   if "load shed" in c.lower()]
            self.load_shed_data = load_shed_data[col[0]].to_numpy(np.float64)
        ts = datasets.time_series
        if ts is None or grab_column(ts, CRIT_COL) is None:
            raise TimeseriesDataError(
                f"Reliability requires a {CRIT_COL!r} column")
        self.critical_load: Optional[pd.Series] = None
        self.requirement: Optional[np.ndarray] = None
        self.min_soe_df: Optional[pd.DataFrame] = None
        self.soe_profiles: Optional[pd.DataFrame] = None
        self.outage_contribution_df: Optional[pd.DataFrame] = None
        self.outage_soe_profile: Optional[pd.DataFrame] = None
        self.dg_rating = 0.0                          # n-2 reserve margin

    # ------------------------------------------------------------------
    def _prepare(self, index: pd.DatetimeIndex) -> None:
        ts = self.datasets.time_series.loc[index]
        self.critical_load = pd.Series(grab_column(ts, CRIT_COL), index=index)
        cov = int(np.round(self.outage_duration / self.dt)) or 1
        self.coverage_steps = cov
        self.requirement = rolling_forward_sum(
            self.critical_load.to_numpy(), cov) * self.dt

    # ------------------------------------------------------------------
    def _der_mix(self, ders) -> Dict:
        """Aggregate DER properties for the outage walk (reference
        ``get_der_mix_properties``, Reliability.py:276-332)."""
        props = {"charge max": 0.0, "discharge max": 0.0, "soe min": 0.0,
                 "soe max": 0.0, "energy rating": 0.0, "rte": 1.0,
                 "rte list": []}
        T = len(self.critical_load)
        pv_max = np.zeros(T)
        pv_vari = np.zeros(T)
        largest_gamma = 0.0
        dg_max = 0.0
        for d in ders:
            ttype = d.technology_type
            if ttype == "Intermittent Resource":
                gen = d.maximum_generation_series(self.critical_load.index)
                pv_max += gen
                pv_vari += gen * getattr(d, "nu", 1.0)
                largest_gamma = max(largest_gamma, getattr(d, "gamma", 1.0))
            elif ttype == "Generator":
                rating = getattr(d, "max_power_out", 0.0)
                dg_max += rating
                # n-2: hold the LARGEST single unit out of the walk
                # (reference Reliability.py:328-330 dg_rating margin)
                self.dg_rating = max(self.dg_rating, rating)
            elif ttype == "Energy Storage System":
                props["rte list"].append(d.rte)
                props["soe min"] += d.operational_min_energy()
                props["soe max"] += d.operational_max_energy()
                props["charge max"] += d.charge_capacity()
                props["discharge max"] += d.discharge_capacity()
                props["energy rating"] += d.energy_capacity()
        if self.n_2:
            dg_max -= self.dg_rating
        if props["rte list"]:
            # deterministic worst-rte (divergence from random.choice, see
            # module docstring)
            props["rte"] = float(min(props["rte list"]))
        gen = np.full(T, dg_max)
        return {"props": props, "gen": gen, "pv_max": pv_max,
                "pv_vari": pv_vari, "gamma": largest_gamma}

    def _checks(self, mix) -> tuple:
        """Full-horizon reliability/demand/energy check arrays (reference
        ``data_process`` rounding semantics, Reliability.py:448-487).  The
        load-shed percentage applies by outage STEP, not timestep, so it
        enters inside the walk only when shedding is flat; for per-step
        shed curves we conservatively apply step-0 (=100%) here and the
        shaped curve in the sizing LP."""
        crit = self.critical_load.to_numpy()
        if self.load_shed and self.load_shed_data is not None:
            crit = crit * (self.load_shed_data[0] / 100.0)
        demand_left = np.around(crit - mix["gen"] - mix["pv_max"], 5)
        reliability_check = np.around(crit - mix["gen"] - mix["pv_vari"], 5)
        energy_check = reliability_check * mix["gamma"]
        return reliability_check, demand_left, energy_check

    def _walk(self, mix, init_soe: np.ndarray, L: int):
        rc, dl, ec = self._checks(mix)
        p = mix["props"]
        cov, prof = _simulate_all_outages(
            jnp.asarray(rc), jnp.asarray(dl), jnp.asarray(ec),
            jnp.asarray(init_soe, jnp.float64 if jax.config.jax_enable_x64
                        else jnp.float32),
            p["charge max"], p["discharge max"], p["soe min"], p["soe max"],
            p["rte"], self.dt, L)
        return np.asarray(cov), np.asarray(prof)

    # ------------------------------------------------------------------
    # pre-dispatch: min-SOE schedule -> system requirement
    # ------------------------------------------------------------------
    def min_soe_schedule(self, ders, index: pd.DatetimeIndex) -> Optional[pd.DataFrame]:
        """Per-timestep minimum SOE so a target-length outage starting there
        is covered (reference ``min_soe_iterative``, Reliability.py:685-732:
        effective swing of the simulated profile from the initial SOC)."""
        if self.critical_load is None:
            self._prepare(index)
        mix = self._der_mix(ders)
        p = mix["props"]
        if p["energy rating"] <= 0:
            return None
        L = self.coverage_steps
        init = np.full(len(index), self.soc_init * p["energy rating"])
        cov, prof = self._walk(mix, init, L)
        # profile incl. the initial soe at the front
        full = np.concatenate([init[:, None], prof], axis=1)
        # dead steps are zero-filled; effective swing over surviving steps
        steps = np.arange(L + 1)[None, :]
        alive = steps <= np.minimum(cov, L)[:, None]
        vals = np.where(alive, full, np.nan)
        swing = np.nanmax(vals, axis=1) - np.nanmin(vals, axis=1)
        self.min_soe_df = pd.DataFrame({"soe": swing}, index=index)
        self.soe_profiles = pd.DataFrame(
            {f"Reliability min SOE profile {k}":
             (prof[:, k] if k < prof.shape[1] else np.zeros(len(index)))
             for k in range(min(L, 2))}, index=index)
        return self.min_soe_df

    def system_requirements(self, ders, years, index) -> List[SystemRequirement]:
        if self.post_facto_only:
            return []
        self._prepare(index)
        self.min_soe_schedule(ders, index)
        if self.min_soe_df is None:
            return []
        return [SystemRequirement("energy", "min", "Reliability",
                                  self.min_soe_df["soe"])]

    # ------------------------------------------------------------------
    # results
    # ------------------------------------------------------------------
    def timeseries_report(self, index) -> pd.DataFrame:
        if self.critical_load is None:
            self._prepare(index)
        out = pd.DataFrame(index=index)
        if not self.post_facto_only:
            out["Total Critical Load (kWh)"] = self.requirement
        out[CRIT_COL] = self.critical_load
        if self.min_soe_df is not None:
            out["Reliability min State of Energy (kWh)"] = self.min_soe_df["soe"]
            if self.soe_profiles is not None:
                for c in self.soe_profiles.columns:
                    out[c] = self.soe_profiles[c]
        return out

    def load_coverage_probability(self, ders, results: pd.DataFrame
                                  ) -> pd.DataFrame:
        """LCPC: simulate an outage at every timestep; P(cover len) =
        fraction of feasible starts that survive >= len (reference
        Reliability.py:876-966 incl. end-truncation accounting)."""
        index = results.index
        if self.critical_load is None:
            self._prepare(index)
        mix = self._der_mix(ders)
        p = mix["props"]
        T = len(index)
        L = int(np.round(self.max_outage_duration / self.dt))
        if p["energy rating"] > 0:
            if "Aggregated State of Energy (kWh)" in results and \
                    not self.post_facto_only:
                init = results["Aggregated State of Energy (kWh)"].to_numpy()
            else:
                init = np.full(T, self.soc_init * p["energy rating"])
        else:
            init = np.zeros(T)
        cov, prof = self._walk(mix, init, L)
        # cap coverage at steps remaining in the horizon
        cov = np.minimum(cov, T - np.arange(T))
        freq = np.bincount(cov.astype(int), minlength=L + 1)
        probs = []
        lengths = np.arange(1, L + 1)
        for k in lengths:
            covered = freq[k:].sum()
            possible = T - k + 1
            probs.append(covered / possible)
        self.outage_soe_profile = pd.DataFrame(
            {h: prof[:, h - 1] for h in range(1, L + 1)}, index=index)
        return pd.DataFrame({
            "Outage Length (hrs)": lengths * self.dt,
            "Load Coverage Probability (%)": probs,
        }).set_index("Outage Length (hrs)")

    def contribution_summary(self, ders, results: pd.DataFrame
                             ) -> pd.DataFrame:
        """Split the outage energy requirement across PV -> storage -> fuel
        (reference Reliability.py:806-874 waterfall order)."""
        index = results.index
        outage_energy = pd.Series(self.requirement, index=index)
        cols = {}
        pv = [d for d in ders if d.technology_type == "Intermittent Resource"]
        if pv:
            agg = np.zeros(len(index))
            for d in pv:
                agg += d.maximum_generation_series(index)
            pv_e = pd.Series(rolling_forward_sum(agg, self.coverage_steps)
                             * self.dt, index=index)
            net = outage_energy - pv_e
            outage_energy = net.clip(lower=0)
            pv_e = pv_e + net.clip(upper=0)
            cols["PV Outage Contribution (kWh)"] = pv_e
        ess = [d for d in ders if d.technology_type == "Energy Storage System"]
        if ess:
            if "Aggregated State of Energy (kWh)" in results:
                soe = results["Aggregated State of Energy (kWh)"]
            else:
                soe = pd.Series(0.0, index=index)
            net = outage_energy - soe
            outage_energy = net.clip(lower=0)
            cols["Storage Outage Contribution (kWh)"] = soe + net.clip(upper=0)
        gens = [d for d in ders if d.technology_type == "Generator"]
        if gens:
            cols["ICE Outage Contribution (kWh)"] = outage_energy
        self.outage_contribution_df = pd.DataFrame(cols, index=index)
        return self.outage_contribution_df

    def drill_down_dfs(self, results: pd.DataFrame, dt: float
                       ) -> Dict[str, pd.DataFrame]:
        return {}  # populated via drill_down_reports (needs the DER list)

    def drill_down_reports(self, ders, results: pd.DataFrame
                           ) -> Dict[str, pd.DataFrame]:
        TellUser.info("Starting load coverage calculation...")
        out = {"load_coverage_prob": self.load_coverage_probability(ders, results)}
        out["lcp_outage_soe_profiles"] = self.outage_soe_profile
        if not self.post_facto_only:
            out["outage_energy_contributions"] = \
                self.contribution_summary(ders, results)
        TellUser.info("Finished load coverage calculation.")
        return out
