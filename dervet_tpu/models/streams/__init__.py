"""Value-stream registry (mirrors VS_CLASS_MAP, MicrogridScenario.py:83-98)."""
from __future__ import annotations


def registry():
    from .da import DAEnergyTimeShift
    reg = {
        "DA": DAEnergyTimeShift,
    }
    try:
        from .retail import EnergyTimeShift, DemandChargeReduction
        reg["retailTimeShift"] = EnergyTimeShift
        reg["DCM"] = DemandChargeReduction
    except ImportError:
        pass
    try:
        from .markets import (FrequencyRegulation, SpinningReserve,
                              NonspinningReserve, LoadFollowing)
        reg.update({"FR": FrequencyRegulation, "SR": SpinningReserve,
                    "NSR": NonspinningReserve, "LF": LoadFollowing})
    except ImportError:
        pass
    try:
        from .programs import (Backup, Deferral, DemandResponse,
                               ResourceAdequacy, UserConstraints, VoltVar)
        reg.update({"Backup": Backup, "Deferral": Deferral,
                    "DR": DemandResponse, "RA": ResourceAdequacy,
                    "User": UserConstraints, "Volt": VoltVar})
    except ImportError:
        pass
    try:
        from .reliability import Reliability
        reg["Reliability"] = Reliability
    except ImportError:
        pass
    return reg
