"""Value-stream contract for the LP-block architecture.

Replaces the reference's storagevet ``ValueStream`` base surface
(SURVEY.md §2.8): each service emits objective cost vectors and
constraint rows into the shared :class:`~dervet_tpu.ops.lp.LPBuilder`,
can post system requirements (min/max energy/power profiles the POI
enforces), and reports its timeseries/proforma contributions afterwards.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from ...ops.lp import LPBuilder
from ...scenario.window import WindowContext


class SystemRequirement:
    """A profile requirement a value stream imposes on the aggregate system
    (reference: storagevet.SystemRequirement.Requirement surface —
    Requirement(kind, sense, source_name, array))."""

    def __init__(self, kind: str, sense: str, source: str, series: pd.Series):
        # import limits are expressed as 'poi export' minima (net export =
        # -import), so a single sign convention reaches the POI
        assert kind in ("energy", "charge", "discharge", "poi export")
        assert sense in ("min", "max")
        self.kind = kind
        self.sense = sense
        self.source = source
        self.series = series  # indexed by timestep

    def window_array(self, index: pd.DatetimeIndex) -> np.ndarray:
        return self.series.reindex(index).to_numpy(dtype=np.float64)


class ValueStream:
    """Base service/value stream."""

    #: fill-forward behavior of this stream's proforma columns: True means
    #: escalate at ``proforma_growth`` (which defaults to the stream's
    #: growth key); False means the value is paid only in optimized years
    fill_forward: bool = True
    #: optional override of the fill-forward escalation rate (fraction/yr);
    #: None means "use the stream's growth key"
    proforma_growth: Optional[float] = None

    def __init__(self, tag: str, keys: Dict, scenario: Dict, datasets):
        self.tag = tag
        self.keys = keys
        self.scenario = scenario
        self.datasets = datasets
        self.name = tag

    # ---------- pre-loop ------------------------------------------------
    def system_requirements(self, ders, years: List[int],
                            index: pd.DatetimeIndex) -> List[SystemRequirement]:
        return []

    # ---------- per-window LP assembly ----------------------------------
    def build(self, b: LPBuilder, ctx: WindowContext, ders) -> None:
        """Add objective terms / variables / constraints for one window."""

    # ---------- results -------------------------------------------------
    def timeseries_report(self, index: pd.DatetimeIndex) -> pd.DataFrame:
        return pd.DataFrame(index=index)

    def monthly_report(self) -> pd.DataFrame:
        return pd.DataFrame()

    def proforma_report(self, opt_years: List[int], poi,
                        results: pd.DataFrame) -> Optional[pd.DataFrame]:
        """Per-year $ rows (positive = benefit), column named after the
        stream; index pd.Period years."""
        return None

    def drill_down_dfs(self, results: pd.DataFrame, dt: float
                       ) -> Dict[str, pd.DataFrame]:
        """Extra output frames (reference: drill-down CSVs, §2.7)."""
        return {}
