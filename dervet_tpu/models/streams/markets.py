"""Ancillary-service market value streams: FR, SR, NSR, LF.

Re-implements the behavior of the storagevet market streams
``FrequencyRegulation`` (FR), ``SpinningReserve`` (SR),
``NonspinningReserve`` (NSR) and ``LoadFollowing`` (LF) (SURVEY.md §2.8;
wired at dervet/MicrogridScenario.py:83-98) on the LP-block architecture:

* each service owns aggregate capacity-bid variables per window (``up``
  raises injection, ``down`` raises absorption); revenue = capacity price x
  bid, with expected-throughput energy settled at the DA price via the
  ``eou``/``eod`` (kWh/kW-hr) factors where the service defines them
* bids register in ``ctx.market_bids``; the POI posts the JOINT headroom
  rows (all services share DER headroom) and SOE-reservation rows (storage
  must hold ``duration`` hours of energy per awarded kW)
* optional time-series bid bounds (``u_ts_constraints``/``d_ts_constraints``
  / ``ts_constraints`` keys) read the reference's min/max columns, e.g.
  'FR Reg Up Max (kW)', 'SR Max (kW)'

Design divergence vs the reference (documented): expected regulation
throughput is settled financially but treated as energy-neutral in the SOE
evolution; the reference's per-ESS ``uenergy`` bookkeeping shifts SOE by
the expected throughput.  The reference's own goldens for market cases
assert only that the run completes (test_3battery.py, SURVEY.md §4).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import pandas as pd

from ...ops.lp import LPBuilder
from ...scenario.window import WindowContext, grab_column
from ...utils.errors import TimeseriesDataError
from .base import ValueStream

DA_PRICE_COL = "DA Price ($/kWh)"

# objective_values column carrying the deterministic tiebreak-tilt term
# (see MarketService.TIEBREAK_EPS): reported EXPLICITLY so the labeled
# per-stream components reconcile exactly — "Total Objective" subtracts
# this term (the tilt is a solver-only vertex selector, not a revenue),
# so sum(labeled components excluding this column) == Total Objective
# to float64 precision, and the invariant audit asserts it
TILT_LABEL = "Tiebreak Tilt"


class MarketService(ValueStream):
    """Shared machinery for capacity-bid market services."""

    #: (direction, price column, ts-bound column stem, eou/eod key)
    directions: List = []

    def __init__(self, tag: str, keys, scenario, datasets):
        super().__init__(tag, keys, scenario, datasets)
        self.growth = float(keys.get("growth", 0) or 0) / 100.0
        self.duration = float(keys.get("duration", 0) or 0)
        self.combined_market = bool(keys.get("CombinedMarket", False))
        if datasets.time_series is None:
            raise TimeseriesDataError(f"{tag} requires a time series")
        for _, price_col, _, _ in self.directions:
            if grab_column(datasets.time_series, price_col) is None:
                raise TimeseriesDataError(
                    f"{tag} requires a {price_col!r} column")

    # throughput factor (kWh of expected dispatch per kW-hr of bid);
    # scalar or a per-timestep array for this window
    def throughput(self, direction: str, ctx: WindowContext):
        return 0.0

    def _bound_cols(self, stem: str):
        return f"{stem} Max (kW)", f"{stem} Min (kW)"

    def _use_ts_bounds(self, direction: str) -> bool:
        return False

    #: deterministic tie-break rank: when two services price capacity
    #: identically the optimum is a face (HiGHS returns a vertex, PDHG a
    #: face point, and per-column revenue attribution diverges between
    #: backends — the r4 DEGENERATE_SPLIT carve-out).  A relative tilt of
    #: TIEBREAK_EPS x rank on each service's OPTIMIZATION price makes the
    #: split unique while perturbing each tilted stream's price by at most
    #: TIEBREAK_EPS x max(rank) = 4e-3 relative (rank 4 = LF); reporting
    #: (proforma/NPV) always uses the untilted price.  The labeled
    #: per-stream revenue vectors exclude the tilt; the tilt itself is
    #: reported as the explicit TILT_LABEL column and SUBTRACTED from the
    #: reported "Total Objective" (scenario.apply_subgroup), so the
    #: labeled components sum exactly to the reported total — the solver
    #: optimizes the tilted objective, reporting publishes the untilted
    #: one.  1e-3, not 1e-4: the tilt gradient must dominate PDHG's
    #: convergence tolerance (eps_rel 1e-4) for the iterate to actually
    #: land on the preferred vertex — at 1e-4 the split still wandered
    #: ~1.5% of a column's scale (input 008, r5).
    TIEBREAK_RANK = {"FR": 1, "SR": 2, "NSR": 3, "LF": 4}
    TIEBREAK_EPS = 1e-3

    def build(self, b: LPBuilder, ctx: WindowContext, ders) -> None:
        scale = ctx.dt * ctx.annuity_scalar
        da_price = ctx.col(DA_PRICE_COL)
        tilt = 1.0 - self.TIEBREAK_EPS * self.TIEBREAK_RANK.get(self.tag, 0)
        refs = {}
        for direction, price_col, stem, _ in self.directions:
            price = ctx.col(price_col)
            lb, ub = 0.0, np.inf
            if self._use_ts_bounds(direction):
                up_col, lo_col = self._bound_cols(stem)
                hi = ctx.col(up_col)
                lo = ctx.col(lo_col)
                if hi is not None:
                    ub = hi
                if lo is not None:
                    lb = np.maximum(lo, 0.0)
            ref = b.var(f"{self.tag}/{direction}", ctx.T, lb=lb, ub=ub)
            refs[direction] = ref
            # capacity revenue (negative cost).  The labeled (reported)
            # vector stays UNTILTED — objective_values must not be
            # biased per stream — while the tilt rides under its own
            # TILT_LABEL column: only the optimizer pays it, and the
            # reported total subtracts it back out (apply_subgroup).
            b.add_cost(ref, -price * scale, label=self.tag)
            if tilt != 1.0:
                b.add_cost(ref, price * scale * (1.0 - tilt),
                           label=TILT_LABEL)
            # expected-throughput energy settlement at DA price: up sells
            # energy (revenue), down absorbs energy (cost); k is kWh per
            # kW-hr of award so the single dt in `scale` converts the
            # award-hours, no extra dt
            k = self.throughput(direction, ctx)
            if np.any(k) and da_price is not None:
                sign = -1.0 if direction == "up" else +1.0
                b.add_cost(ref, sign * k * da_price * scale,
                           label=f"{self.tag} energy settlement")
            ctx.market_bids.setdefault(direction, []).append(
                (ref, self.duration))
        if self.combined_market and "up" in refs and "down" in refs:
            # single combined market: up and down awards are equal
            # (reference: FR CombinedMarket semantics)
            b.add_rows(f"{self.tag}/combined",
                       [(refs["up"], 1.0), (refs["down"], -1.0)], "eq", 0.0)

    # ---------- results -------------------------------------------------
    dispatch: Optional[Dict[str, pd.Series]] = None

    def timeseries_report(self, index) -> pd.DataFrame:
        out = pd.DataFrame(index=index)
        ts = self.datasets.time_series.loc[index]
        for direction, price_col, stem, _ in self.directions:
            price = grab_column(ts, price_col)
            if price is not None:
                out[price_col] = price
            if self.dispatch is not None and direction in self.dispatch:
                label = "Up" if direction == "up" else "Down"
                out[f"{self.tag} Awarded {label} (kW)"] = \
                    self.dispatch[direction]
        return out

    def store_dispatch(self, index, solution: Dict[str, np.ndarray]) -> None:
        self.dispatch = {}
        for direction, _, _, _ in self.directions:
            arr = solution.get(f"{self.tag}/{direction}")
            if arr is not None:
                self.dispatch[direction] = pd.Series(arr, index=index)

    def proforma_report(self, opt_years, poi, results) -> Optional[pd.DataFrame]:
        if self.dispatch is None:
            return None
        dt = float(self.scenario.get("dt", 1))
        ts = self.datasets.time_series
        cols: Dict[str, Dict] = {}
        for direction, price_col, stem, _ in self.directions:
            label = f"{self.tag} {'Reg Up' if direction == 'up' else 'Reg Down'}" \
                if len(self.directions) > 1 else f"{self.tag} Capacity Payment"
            award = self.dispatch.get(direction)
            if award is None:
                continue
            price = pd.Series(grab_column(ts, price_col), index=ts.index)
            rows = {}
            for yr in opt_years:
                mask = award.index.year == yr
                rows[pd.Period(yr, freq="Y")] = float(
                    (price.reindex(award.index)[mask] * award[mask]).sum() * dt)
            cols[label] = rows
        return pd.DataFrame(cols) if cols else None


class FrequencyRegulation(MarketService):
    """FR: symmetric regulation with separate up/down prices (or a single
    combined market), expected throughput ``eou``/``eod``."""

    def __init__(self, keys, scenario, datasets):
        self.directions = [
            ("up", "Reg Up Price ($/kW)", "FR Reg Up", "eou"),
            ("down", "Reg Down Price ($/kW)", "FR Reg Down", "eod"),
        ]
        if bool(keys.get("CombinedMarket", False)) and \
                datasets.time_series is not None and \
                grab_column(datasets.time_series, "FR Price ($/kW)") is not None:
            self.directions = [
                ("up", "FR Price ($/kW)", "FR Reg Up", "eou"),
                ("down", "FR Price ($/kW)", "FR Reg Down", "eod"),
            ]
        super().__init__("FR", keys, scenario, datasets)
        self.eou = float(keys.get("eou", 0) or 0)
        self.eod = float(keys.get("eod", 0) or 0)

    def throughput(self, direction: str, ctx: WindowContext):
        return self.eou if direction == "up" else self.eod

    def _use_ts_bounds(self, direction: str) -> bool:
        key = "u_ts_constraints" if direction == "up" else "d_ts_constraints"
        return bool(self.keys.get(key, False))


class LoadFollowing(MarketService):
    """LF: like FR with its own price/energy-option columns."""

    directions = [
        ("up", "LF Up Price ($/kW)", "LF Reg Up", None),
        ("down", "LF Down Price ($/kW)", "LF Reg Down", None),
    ]

    def __init__(self, keys, scenario, datasets):
        super().__init__("LF", keys, scenario, datasets)

    def throughput(self, direction: str, ctx: WindowContext):
        col = "LF Energy Option Up (kWh/kW-hr)" if direction == "up" \
            else "LF Energy Option Down (kWh/kW-hr)"
        arr = ctx.col(col)
        return arr if arr is not None else 0.0

    def _use_ts_bounds(self, direction: str) -> bool:
        key = "u_ts_constraints" if direction == "up" else "d_ts_constraints"
        return bool(self.keys.get(key, False))


class SpinningReserve(MarketService):
    """SR: up-only reserve priced by 'SR Price ($/kW)'."""

    directions = [("up", "SR Price ($/kW)", "SR", None)]

    def __init__(self, keys, scenario, datasets):
        super().__init__("SR", keys, scenario, datasets)

    def _use_ts_bounds(self, direction: str) -> bool:
        return bool(self.keys.get("ts_constraints", False))


class NonspinningReserve(MarketService):
    """NSR: up-only reserve priced by 'NSR Price ($/kW)'."""

    directions = [("up", "NSR Price ($/kW)", "NSR", None)]

    def __init__(self, keys, scenario, datasets):
        super().__init__("NSR", keys, scenario, datasets)

    def _use_ts_bounds(self, direction: str) -> bool:
        return bool(self.keys.get("ts_constraints", False))
