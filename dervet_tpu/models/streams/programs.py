"""Program / contract value streams: User, Backup, Deferral, DR, RA, VoltVar.

Re-implements the behavior of the storagevet value streams
``UserConstraints``, ``Backup``, ``Deferral``, ``DemandResponse``,
``ResourceAdequacy`` and ``VoltVar`` (SURVEY.md §2.8; wired at
dervet/MicrogridScenario.py:83-98) on the LP-block architecture.  These
streams impose profiles/events on the aggregate system (as
:class:`SystemRequirement` objects the POI turns into rows) and book
contract revenue in the proforma; none owns dispatch variables.

Input surface matches the reference datasets:
* time series: 'POI: Max Export (kW)', 'POI: Max Import (kW)',
  'Aggregate Energy Max (kWh)', 'Aggregate Energy Min (kWh)',
  'Deferral Load (kW)', 'RA Active (y/n)', 'VAR Reservation (%)',
  'Site Load (kW)'
* monthly data: 'Backup Price ($/kWh)', 'Backup Energy (kWh)',
  'DR Months (y/n)', 'DR Capacity (kW)', 'DR Capacity Price ($/kW)',
  'DR Energy Price ($/kWh)', 'RA Capacity Price ($/kW)'

Documented divergences from the (absent) storagevet sources: DR/RA event
days are selected deterministically as the top-load days inside the program
window; the reference's exact selection is unrecoverable from the snapshot
and its own tests only assert completion (SURVEY.md §4).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import pandas as pd

from ...ops.lp import LPBuilder
from ...scenario.window import WindowContext, grab_column
from ...utils.errors import ParameterError, TellUser, TimeseriesDataError
from .base import SystemRequirement, ValueStream


def _monthly_series(monthly: Optional[pd.DataFrame], col: str,
                    index: pd.DatetimeIndex,
                    default: Optional[float] = None) -> Optional[pd.Series]:
    """Broadcast a (Year, Month)-indexed monthly column over timesteps.
    With ``default`` set, a missing column yields a constant series instead
    of None (optional program columns)."""
    if monthly is None or col not in monthly.columns:
        if default is None:
            return None
        return pd.Series(float(default), index=index)
    key = pd.MultiIndex.from_arrays([index.year, index.month])
    vals = monthly[col].reindex(key).to_numpy(dtype=np.float64)
    return pd.Series(vals, index=index)


class UserConstraints(ValueStream):
    """User-defined aggregate limits from time-series columns, paid a fixed
    yearly price (reference: storagevet UserConstraints surface; schema
    User.price)."""

    fill_forward = False      # paid only in optimized years (step2 golden)

    POI_EXPORT = "POI: Max Export (kW)"
    POI_IMPORT = "POI: Max Import (kW)"
    ENE_MAX = "Aggregate Energy Max (kWh)"
    ENE_MIN = "Aggregate Energy Min (kWh)"

    def __init__(self, keys, scenario, datasets):
        super().__init__("User", keys, scenario, datasets)
        self.price = float(keys.get("price", 0) or 0)
        ts = datasets.time_series
        if ts is None:
            raise TimeseriesDataError("User constraints require a time series")
        self.found = [c for c in (self.POI_EXPORT, self.POI_IMPORT,
                                  self.ENE_MAX, self.ENE_MIN)
                      if grab_column(ts, c) is not None]
        if not self.found:
            raise TimeseriesDataError(
                "User constraints active but none of the constraint columns "
                f"({self.POI_EXPORT!r}, {self.POI_IMPORT!r}, {self.ENE_MAX!r}, "
                f"{self.ENE_MIN!r}) are in the time series")

    def system_requirements(self, ders, years, index) -> List[SystemRequirement]:
        ts = self.datasets.time_series.loc[index]
        out = []

        def col(name):
            arr = grab_column(ts, name)
            return None if arr is None else pd.Series(arr, index=index)

        exp = col(self.POI_EXPORT)
        if exp is not None:
            out.append(SystemRequirement("poi export", "max", "User", exp))
        imp = col(self.POI_IMPORT)
        if imp is not None:
            # the reference's import column is negative-valued (import is
            # negative net export); net export >= import limit
            out.append(SystemRequirement("poi export", "min", "User", imp))
        emax = col(self.ENE_MAX)
        if emax is not None:
            out.append(SystemRequirement("energy", "max", "User", emax))
        emin = col(self.ENE_MIN)
        if emin is not None:
            out.append(SystemRequirement("energy", "min", "User", emin))
        return out

    def proforma_report(self, opt_years, poi, results) -> Optional[pd.DataFrame]:
        rows = {pd.Period(yr, freq="Y"): self.price for yr in opt_years}
        return pd.DataFrame({"User Constraints Value": rows})


class Backup(ValueStream):
    """Backup energy reservation: hold a monthly energy floor in storage,
    paid per kWh reserved (reference: storagevet Backup surface; monthly
    'Backup Energy (kWh)' / 'Backup Price ($/kWh)')."""

    def __init__(self, keys, scenario, datasets):
        super().__init__("Backup", keys, scenario, datasets)
        if datasets.monthly is None or \
                "Backup Energy (kWh)" not in datasets.monthly.columns:
            raise TimeseriesDataError(
                "Backup requires monthly 'Backup Energy (kWh)' data")

    def system_requirements(self, ders, years, index) -> List[SystemRequirement]:
        energy = _monthly_series(self.datasets.monthly, "Backup Energy (kWh)",
                                 index)
        return [SystemRequirement("energy", "min", "Backup", energy.fillna(0.0))]

    def monthly_report(self) -> pd.DataFrame:
        m = self.datasets.monthly
        cols = [c for c in ("Backup Energy (kWh)", "Backup Price ($/kWh)")
                if c in m.columns]
        return m[cols].copy()

    def proforma_report(self, opt_years, poi, results) -> Optional[pd.DataFrame]:
        m = self.datasets.monthly
        if "Backup Price ($/kWh)" not in m.columns:
            return None
        rows = {}
        for yr in opt_years:
            sel = m.loc[[i for i in m.index if i[0] == yr]]
            rows[pd.Period(yr, freq="Y")] = float(
                (sel["Backup Energy (kWh)"] * sel["Backup Price ($/kWh)"]).sum())
        return pd.DataFrame({"Backup Plan": rows})


class Deferral(ValueStream):
    """T&D upgrade deferral: keep the substation flow within planned limits
    while serving the deferral load; earn the deferral price for each year
    the upgrade stays deferred (reference: storagevet Deferral surface +
    MicrogridServiceAggregator.py:81-107 min-size hooks)."""

    LOAD_COL = "Deferral Load (kW)"

    def __init__(self, keys, scenario, datasets):
        super().__init__("Deferral", keys, scenario, datasets)
        g = lambda k, d=0.0: float(keys.get(k, d) or 0.0)
        self.price = g("price")                       # $/yr deferred
        self.growth = g("growth") / 100.0             # deferral LOAD growth
        # the contract price is a flat dollar value — the growth key is a
        # load-projection rate, not a price escalator
        self.proforma_growth = 0.0
        self.planned_load_limit = g("planned_load_limit")
        self.reverse_power_flow_limit = g("reverse_power_flow_limit")  # <= 0
        self.min_year_objective = int(g("min_year_objective"))
        ts = datasets.time_series
        if ts is None or grab_column(ts, self.LOAD_COL) is None:
            raise TimeseriesDataError(
                f"Deferral requires a {self.LOAD_COL!r} column")
        self.deferral_df: Optional[pd.DataFrame] = None

    def system_requirements(self, ders, years, index) -> List[SystemRequirement]:
        ts = self.datasets.time_series.loc[index]
        dload = pd.Series(grab_column(ts, self.LOAD_COL), index=index)
        # substation import = deferral load - net export <= planned limit
        #   -> net export >= deferral load - planned limit
        lo = dload - self.planned_load_limit
        # substation reverse flow = net export - deferral load
        #   <= |reverse limit|  -> net export <= deferral load + |limit|
        hi = dload + abs(self.reverse_power_flow_limit)
        return [SystemRequirement("poi export", "min", "Deferral", lo),
                SystemRequirement("poi export", "max", "Deferral", hi)]

    # ---------- yearly deferral feasibility analysis --------------------
    def deferral_analysis(self, ders, opt_years: List[int],
                          end_year: int) -> pd.DataFrame:
        """Per-year power/energy requirement under load growth vs the DER
        fleet's capability (reference: Deferral.deferral_df consumed at
        MicrogridServiceAggregator.py:93-98)."""
        ts = self.datasets.time_series
        # anchor the growth projection on the BASE optimized year only —
        # later (possibly growth-synthesized) years would double-count the
        # fill's growth
        base_mask = ts.index.year == min(opt_years)
        ts = ts[base_mask] if base_mask.any() else ts
        index = ts.index
        dload = np.asarray(grab_column(ts, self.LOAD_COL))
        dt = float(self.scenario.get("dt", 1))
        dis_cap = sum(getattr(d, "discharge_capacity", lambda: 0.0)()
                      for d in ders)
        ene_cap = sum(getattr(d, "energy_capacity", lambda: 0.0)()
                      for d in ders)
        base_year = opt_years[0]
        rows = []
        for yr in range(base_year, end_year + 1):
            scale = (1.0 + self.growth) ** (yr - base_year)
            over = np.maximum(dload * scale - self.planned_load_limit, 0.0)
            p_req = float(over.max()) if len(over) else 0.0
            # max energy over contiguous overload runs
            e_req = 0.0
            run = 0.0
            for v in over:
                run = run + v * dt if v > 0 else 0.0
                e_req = max(e_req, run)
            rows.append({"Year": yr, "Power Requirement (kW)": p_req,
                         "Energy Requirement (kWh)": e_req,
                         "Deferral Possible": bool(p_req <= dis_cap
                                                   and e_req <= ene_cap)})
        self.deferral_df = pd.DataFrame(rows).set_index("Year")
        return self.deferral_df

    @property
    def min_years(self) -> int:
        if self.deferral_df is None:
            return 0
        ok = self.deferral_df["Deferral Possible"]
        n = 0
        for v in ok:
            if not v:
                break
            n += 1
        return n

    def proforma_report(self, opt_years, poi, results) -> Optional[pd.DataFrame]:
        rows = {pd.Period(yr, freq="Y"): self.price for yr in opt_years}
        return pd.DataFrame({"Deferral: Avoided Upgrade": rows})

    def drill_down_dfs(self, results, dt) -> Dict[str, pd.DataFrame]:
        if self.deferral_df is None:
            return {}
        return {"deferral_results": self.deferral_df}


class DemandResponse(ValueStream):
    """DR program: commit capacity on the worst `days` days of each DR
    month inside the program hours (reference: storagevet DemandResponse
    surface; keys days/length/program_start_hour/program_end_hour/weekend/
    day_ahead).

    day_ahead=1: events are known a day ahead — the committed discharge is
    scheduled (aggregate discharge-min requirement on event steps).
    day_ahead=0 (day-of): events may be called any program day — storage
    holds capacity x length of energy through every program-hour step.
    """

    def __init__(self, keys, scenario, datasets):
        super().__init__("DR", keys, scenario, datasets)
        self.growth = float(keys.get("growth", 0) or 0) / 100.0
        self.days = int(float(keys.get("days", 0) or 0))
        self.weekend = bool(keys.get("weekend", False))
        self.day_ahead = bool(keys.get("day_ahead", False))
        start = keys.get("program_start_hour")
        end = keys.get("program_end_hour")
        length = keys.get("length")

        def _num(v):
            try:
                f = float(v)
                return None if np.isnan(f) else f
            except (TypeError, ValueError):
                return None

        start, end, length = _num(start), _num(end), _num(length)
        if start is None:
            raise ParameterError("DR requires program_start_hour")
        # reference semantics: exactly one of length / program_end_hour,
        # the other derived (test inputs 021/022 use nan for the derived one)
        if end is None and length is None:
            raise ParameterError(
                "DR requires either length or program_end_hour")
        if end is None:
            end = start + length - 1
        elif length is None:
            length = end - start + 1
        elif end - start + 1 != length:
            raise ParameterError(
                f"DR length {length} conflicts with program hours "
                f"{start}..{end}")
        self.start_he, self.end_he, self.length = int(start), int(end), float(length)
        if datasets.monthly is None or \
                "DR Capacity (kW)" not in datasets.monthly.columns:
            raise TimeseriesDataError("DR requires monthly 'DR Capacity (kW)'")

    # ---------- event selection ----------------------------------------
    def event_mask(self, index: pd.DatetimeIndex) -> np.ndarray:
        """Boolean mask of committed event steps (top-`days` site-load days
        per active DR month, program hours only)."""
        monthly = self.datasets.monthly
        # a missing 'DR Months (y/n)' column means every month participates
        active = _monthly_series(monthly, "DR Months (y/n)", index, default=1.0)
        he = np.asarray(index.hour) + 1
        hours = (he >= self.start_he) & (he <= self.end_he)
        if not self.weekend:
            hours &= np.asarray(index.weekday) < 5
        in_program = hours & (np.asarray(active.fillna(0.0)) > 0)
        site = grab_column(self.datasets.time_series.loc[index],
                           "Site Load (kW)")
        load = np.asarray(site) if site is not None else np.ones(len(index))
        mask = np.zeros(len(index), dtype=bool)
        my = index.to_period("M")
        for m in my.unique():
            sel = np.asarray(my == m) & in_program
            if not sel.any():
                continue
            days = pd.Series(np.where(sel, load, -np.inf),
                             index=index).groupby(index.date).max()
            top = days.nlargest(min(self.days, int((days > -np.inf).sum())))
            event_days = set(top.index)
            day_arr = np.asarray(index.date)
            mask |= sel & np.isin(day_arr, list(event_days))
        return mask

    def system_requirements(self, ders, years, index) -> List[SystemRequirement]:
        cap = _monthly_series(self.datasets.monthly, "DR Capacity (kW)", index)
        cap = cap.fillna(0.0)
        mask = self.event_mask(index)
        if self.day_ahead:
            series = pd.Series(np.where(mask, cap, 0.0), index=index)
            return [SystemRequirement("discharge", "min", "DR", series)]
        # day-of: hold capacity*length of energy through all program steps
        active = _monthly_series(self.datasets.monthly, "DR Months (y/n)",
                                 index, default=1.0)
        he = np.asarray(index.hour) + 1
        hours = (he >= self.start_he) & (he <= self.end_he)
        if not self.weekend:
            hours &= np.asarray(index.weekday) < 5
        program = hours & (np.asarray(active.fillna(0.0)) > 0)
        series = pd.Series(np.where(program, cap * self.length, 0.0),
                           index=index)
        return [SystemRequirement("energy", "min", "DR", series)]

    def monthly_report(self) -> pd.DataFrame:
        m = self.datasets.monthly
        cols = [c for c in ("DR Months (y/n)", "DR Capacity (kW)",
                            "DR Capacity Price ($/kW)",
                            "DR Energy Price ($/kWh)") if c in m.columns]
        return m[cols].copy()

    def proforma_report(self, opt_years, poi, results) -> Optional[pd.DataFrame]:
        m = self.datasets.monthly
        cap_rows, ene_rows = {}, {}
        dt = float(self.scenario.get("dt", 1))
        mask = self.event_mask(results.index)
        eprice = _monthly_series(m, "DR Energy Price ($/kWh)", results.index,
                                 default=0.0).fillna(0.0)
        for yr in opt_years:
            sel = m.loc[[i for i in m.index if i[0] == yr]]
            active = sel.get("DR Months (y/n)", pd.Series(1, index=sel.index))
            cap = sel.get("DR Capacity (kW)", pd.Series(0.0, index=sel.index))
            cprice = sel.get("DR Capacity Price ($/kW)",
                             pd.Series(0.0, index=sel.index))
            cap_rows[pd.Period(yr, freq="Y")] = float(
                ((active > 0) * cap * cprice).sum())
            # energy payment on delivered event energy
            ymask = (results.index.year == yr) & mask
            delivered = -results.loc[ymask, "Net Load (kW)"].clip(upper=0.0)
            ene_rows[pd.Period(yr, freq="Y")] = float(
                (np.asarray(eprice[ymask]) * np.asarray(delivered)).sum() * dt)
        return pd.DataFrame({"DR Capacity Payment": cap_rows,
                             "DR Energy Payment": ene_rows})


class ResourceAdequacy(ValueStream):
    """RA: qualifying capacity payments for system peaks (reference:
    storagevet ResourceAdequacy surface; keys days/length/idmode/dispmode;
    monthly 'RA Capacity Price ($/kW)')."""

    def __init__(self, keys, scenario, datasets):
        super().__init__("RA", keys, scenario, datasets)
        self.growth = float(keys.get("growth", 0) or 0) / 100.0
        self.days = int(float(keys.get("days", 1) or 1))
        self.length = float(keys.get("length", 4) or 4)
        self.dispmode = bool(keys.get("dispmode", False))
        self.idmode = str(keys.get("idmode", "peak by year")).strip().lower()
        if datasets.monthly is None or \
                "RA Capacity Price ($/kW)" not in datasets.monthly.columns:
            raise TimeseriesDataError(
                "RA requires monthly 'RA Capacity Price ($/kW)'")

    def qualifying_capacity(self, ders) -> float:
        """Sustained-discharge capability: storage limited by energy over
        the event length; generators by nameplate."""
        qc = 0.0
        for d in ders:
            if d.technology_type == "Energy Storage System":
                qc += min(d.discharge_capacity(),
                          d.energy_capacity() / max(self.length, 1e-9))
            elif d.technology_type == "Generator":
                qc += getattr(d, "max_power_out", 0.0)
        return qc

    def event_mask(self, index: pd.DatetimeIndex) -> np.ndarray:
        ts = self.datasets.time_series.loc[index]
        flag = grab_column(ts, "RA Active (y/n)")
        if flag is not None and np.any(np.asarray(flag) > 0):
            return np.asarray(flag) > 0
        site = grab_column(ts, "Site Load (kW)")
        load = pd.Series(np.asarray(site) if site is not None else 0.0,
                         index=index)
        mask = np.zeros(len(index), dtype=bool)
        half = int(round(self.length / 2))
        groups = [index.year] if "year" in self.idmode else \
            [index.year, index.month]
        for _, sub in load.groupby(groups):
            peaks = sub.groupby(sub.index.date).max().nlargest(self.days)
            for day in peaks.index:
                day_mask = np.asarray(index.date) == day
                day_load = np.where(day_mask, load, -np.inf)
                center = int(np.argmax(day_load))
                lo = max(0, center - half + 1)
                hi = min(len(index), lo + int(round(self.length)))
                mask[lo:hi] = True
        return mask

    def system_requirements(self, ders, years, index) -> List[SystemRequirement]:
        if not self.dispmode:
            qc = self.qualifying_capacity(ders)
            mask = self.event_mask(index)
            series = pd.Series(np.where(mask, qc * self.length, 0.0),
                               index=index)
            return [SystemRequirement("energy", "min", "RA", series)]
        qc = self.qualifying_capacity(ders)
        mask = self.event_mask(index)
        series = pd.Series(np.where(mask, qc, 0.0), index=index)
        return [SystemRequirement("discharge", "min", "RA", series)]

    def timeseries_report(self, index) -> pd.DataFrame:
        out = pd.DataFrame(index=index)
        out["RA Event (y/n)"] = self.event_mask(index).astype(float)
        return out

    def proforma_report(self, opt_years, poi, results) -> Optional[pd.DataFrame]:
        m = self.datasets.monthly
        qc = self.qualifying_capacity(poi.der_list if poi else [])
        rows = {}
        for yr in opt_years:
            sel = m.loc[[i for i in m.index if i[0] == yr]]
            price = sel["RA Capacity Price ($/kW)"]
            rows[pd.Period(yr, freq="Y")] = float((price * qc).sum())
        return pd.DataFrame({"RA Capacity Payment": rows})


class VoltVar(ValueStream):
    """Volt/VAR support: reserve a fraction of inverter apparent power for
    reactive duty — per-timestep real-power derate on inverter-based DERs
    (reference: storagevet VoltVar surface; 'VAR Reservation (%)' column)."""

    COL = "VAR Reservation (%)"

    def __init__(self, keys, scenario, datasets):
        super().__init__("Volt", keys, scenario, datasets)
        ts = datasets.time_series
        if ts is None or grab_column(ts, self.COL) is None:
            raise TimeseriesDataError(f"VoltVar requires a {self.COL!r} column")

    def build(self, b: LPBuilder, ctx: WindowContext, ders) -> None:
        reserve = np.clip(np.asarray(ctx.col(self.COL)) / 100.0, 0.0, 1.0)
        # P <= S * sqrt(1 - r^2): linear per-timestep derate factor
        derate = np.sqrt(np.maximum(1.0 - reserve ** 2, 0.0))
        for d in ders:
            if d.technology_type == "Energy Storage System":
                # sized ratings derate against the size variable instead of
                # the (zero) numeric rating
                for q, cap, sizing in (
                        ("dis", d.discharge_capacity(),
                         getattr(d, "sizing_dis", False)),
                        ("ch", d.charge_capacity(),
                         getattr(d, "sizing_ch", False))):
                    size_name = d.vname("size_dis" if sizing and
                                        not b.has(d.vname(f"size_{q}"))
                                        else f"size_{q}")
                    if sizing and b.has(size_name):
                        b.add_rows(f"voltvar_{d.vname(q)}",
                                   [(b[d.vname(q)], 1.0),
                                    (b[size_name], -derate[:, None])],
                                   "le", 0.0)
                    else:
                        b.add_rows(f"voltvar_{d.vname(q)}",
                                   [(b[d.vname(q)], 1.0)], "le",
                                   cap * derate)
            elif d.tag == "PV" and b.has(d.vname("gen")):
                # only curtailable PV can respond to a derate; fixed
                # (lb==ub) generation would make the row infeasible
                inv = getattr(d, "inv_max", np.inf)
                if np.isfinite(inv) and getattr(d, "curtail", False):
                    b.add_rows(f"voltvar_{d.vname('gen')}",
                               [(b[d.vname("gen")], 1.0)], "le",
                               inv * derate)

    def timeseries_report(self, index) -> pd.DataFrame:
        out = pd.DataFrame(index=index)
        arr = grab_column(self.datasets.time_series.loc[index], self.COL)
        out[self.COL] = arr
        return out
