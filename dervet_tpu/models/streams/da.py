"""Day-ahead energy time-shift value stream.

Re-implements the behavior of storagevet ``ValueStreams.DAEnergyTimeShift``
(SURVEY.md §2.8; wired at dervet/MicrogridScenario.py:89): the system pays
the day-ahead price for net power drawn from the grid and earns it for net
injection.  As LP blocks this is a pure cost vector: for every DER power
variable, ``-sign * price * dt`` (import costs, export earns), plus a
constant term for fixed loads.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np
import pandas as pd

from ...ops.lp import LPBuilder
from ...scenario.window import WindowContext, grab_column
from ...utils.errors import TimeseriesDataError
from .base import ValueStream

PRICE_COL = "DA Price ($/kWh)"


class DAEnergyTimeShift(ValueStream):

    def __init__(self, keys, scenario, datasets):
        super().__init__("DA", keys, scenario, datasets)
        self.growth = float(keys.get("growth", 0) or 0) / 100.0
        if datasets.time_series is None or \
                grab_column(datasets.time_series, PRICE_COL) is None:
            raise TimeseriesDataError(
                f"DA energy time shift requires a {PRICE_COL!r} column")

    def build(self, b: LPBuilder, ctx: WindowContext, ders) -> None:
        price = ctx.col(PRICE_COL)
        scale = ctx.dt * ctx.annuity_scalar
        for der in ders:
            for ref, sign in der.power_terms(b):
                b.add_cost(ref, -sign * price * scale, label="DA ETS")
        # constant loads priced exactly once, via the POI-computed total
        # (site load + DER fixed loads; see WindowContext.fixed_load)
        if ctx.fixed_load is not None:
            b.add_const_cost(float(np.sum(price * ctx.fixed_load)) * scale,
                             label="DA ETS")

    # ---------- results -------------------------------------------------
    def timeseries_report(self, index) -> pd.DataFrame:
        out = pd.DataFrame(index=index)
        ts = self.datasets.time_series
        price = grab_column(ts.loc[index], PRICE_COL)
        out[PRICE_COL] = price
        return out

    def proforma_report(self, opt_years, poi, results) -> Optional[pd.DataFrame]:
        """DA ETS value per year = sum(price * net power injected * dt)."""
        rows = {}
        price = results[PRICE_COL]
        net = -results["Net Load (kW)"]
        dt = float(self.scenario.get("dt", 1))
        for yr in opt_years:
            mask = results.index.year == yr
            rows[pd.Period(yr, freq="Y")] = float(
                np.sum(price[mask] * net[mask]) * dt)
        return pd.DataFrame({"DA ETS": rows})
