"""Retail value streams: energy time-shift and demand charge management.

Re-implements the behavior of the storagevet ``EnergyTimeShift``
(retailTimeShift tag) and ``DemandChargeReduction`` (DCM tag) value streams
(SURVEY.md §2.8; wired at dervet/MicrogridScenario.py:83-98) on the
LP-block architecture:

* retailTimeShift: the customer pays the tariff energy price for net load
  drawn through the POI each timestep (exports credited at the same retail
  rate — net-metering semantics, matching the reference's symmetric
  ``price * net load`` billing in the frozen ``adv_monthly_bill`` goldens)
* DCM: for every (calendar month x demand billing period) present in an
  optimization window, one scalar peak variable ``d >= net load(t)`` over
  the period's masked timesteps, costed at the period's $/kW value.  The
  reference builds the same per-month maxima via CVXPY ``cvx.max``
  expressions; a scalar epigraph variable is the LP-native equivalent.

Proforma rows are 'Avoided Energy Charge' / 'Avoided Demand Charge':
original bill minus with-DER bill, computed by the shared
:class:`~dervet_tpu.financial.tariff.TariffEngine`.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import pandas as pd

from ...financial.tariff import TariffEngine
from ...ops.lp import LPBuilder
from ...scenario.window import WindowContext
from ...utils.errors import TariffError
from .base import ValueStream


class _TariffStream(ValueStream):
    """Shared tariff plumbing for retailTimeShift and DCM."""

    def __init__(self, tag: str, keys, scenario, datasets):
        super().__init__(tag, keys, scenario, datasets)
        if datasets.tariff is None:
            raise TariffError(f"{tag} requires a customer_tariff_filename "
                              "under the Finance tag")
        self.engine = TariffEngine(datasets.tariff)
        self.growth = float(keys.get("growth", 0) or 0) / 100.0

    # bill frames for drill-downs; net/original load supplied by results
    def monthly_bills(self, net_load: pd.Series, original_load: pd.Series,
                      dt: float):
        return self.engine.monthly_bill(net_load, original_load, dt)


class EnergyTimeShift(_TariffStream):
    """retailTimeShift: minimize retail energy cost of net load."""

    def __init__(self, keys, scenario, datasets):
        super().__init__("retailTimeShift", keys, scenario, datasets)

    def build(self, b: LPBuilder, ctx: WindowContext, ders) -> None:
        price = self.engine.energy_price(ctx.index)
        scale = ctx.dt * ctx.annuity_scalar
        for der in ders:
            for ref, sign in der.power_terms(b):
                # net load = fixed load - sum(sign*var); import costs money
                b.add_cost(ref, -sign * price * scale, label="retailETS")
        if ctx.fixed_load is not None:
            b.add_const_cost(float(price @ ctx.fixed_load) * scale,
                             label="retailETS")

    def timeseries_report(self, index) -> pd.DataFrame:
        out = pd.DataFrame(index=index)
        out["Tariff Energy Price ($/kWh)"] = self.engine.energy_price(index)
        return out

    def proforma_report(self, opt_years, poi, results) -> Optional[pd.DataFrame]:
        rows = {}
        dt = float(self.scenario.get("dt", 1))
        price = results["Tariff Energy Price ($/kWh)"].to_numpy()
        net = results["Net Load (kW)"].to_numpy()
        orig = results["Total Original Load (kW)"].to_numpy()
        years = results.index.year
        for yr in opt_years:
            mask = years == yr
            avoided = float(np.sum(price[mask] * (orig[mask] - net[mask])) * dt)
            rows[pd.Period(yr, freq="Y")] = avoided
        return pd.DataFrame({"Avoided Energy Charge": rows})

    def drill_down_dfs(self, results: pd.DataFrame, dt: float
                       ) -> Dict[str, pd.DataFrame]:
        net = results["Net Load (kW)"]
        orig = results["Total Original Load (kW)"]
        adv, simple = self.monthly_bills(net, orig, dt)
        return {"adv_monthly_bill": adv, "simple_monthly_bill": simple}


class DemandChargeReduction(_TariffStream):
    """DCM: minimize demand charges via per-period peak epigraph variables."""

    def __init__(self, keys, scenario, datasets):
        super().__init__("DCM", keys, scenario, datasets)
        if not self.engine.demand_periods:
            raise TariffError("DCM is active but the tariff has no demand "
                              "billing periods")

    def build(self, b: LPBuilder, ctx: WindowContext, ders) -> None:
        index = ctx.index
        month_year = index.to_period("M")
        load = ctx.fixed_load if ctx.fixed_load is not None \
            else np.zeros(ctx.T)
        terms = []
        for der in ders:
            terms.extend(der.power_terms(b))
        import scipy.sparse as sp
        for my in month_year.unique():
            in_month = np.asarray(month_year == my)
            sub_index = index[in_month]
            for pid, val, mask_local in self.engine.demand_masks(sub_index):
                if not mask_local.any():
                    continue
                full_mask = np.zeros(ctx.T, dtype=bool)
                full_mask[np.nonzero(in_month)[0][mask_local]] = True
                k = int(full_mask.sum())
                d = b.var(f"DCM/{my}/{pid}", 1, lb=0.0)
                # net_load(t) <= d  =>  sum(sign*var(t)) + d >= load(t)
                row_terms = [(d, np.ones((k, 1)))]
                sel_rows = np.nonzero(full_mask)[0]
                for ref, sign in terms:
                    mat = sp.coo_matrix(
                        (np.full(k, sign), (np.arange(k), sel_rows)),
                        shape=(k, ref.size)).tocsr()
                    row_terms.append((ref, mat))
                b.add_rows(f"dcm_{my}_{pid}", row_terms, "ge", load[full_mask])
                b.add_cost(d, val * ctx.annuity_scalar, label="DCM")

    def timeseries_report(self, index) -> pd.DataFrame:
        out = pd.DataFrame(index=index)
        out["Demand Charge Billing Periods"] = \
            self.engine.billing_periods_by_step(index)
        return out

    def proforma_report(self, opt_years, poi, results) -> Optional[pd.DataFrame]:
        dt = float(self.scenario.get("dt", 1))
        net = results["Net Load (kW)"]
        orig = results["Total Original Load (kW)"]
        rows = {}
        adv, _ = self.monthly_bills(net, orig, dt)
        if not len(adv):
            return None
        dem = adv.dropna(subset=["Demand Charge ($)"])
        for yr in opt_years:
            sel = dem[[my.year == yr for my in dem.index]]
            avoided = float((sel["Original Demand Charge ($)"]
                             - sel["Demand Charge ($)"]).sum())
            rows[pd.Period(yr, freq="Y")] = avoided
        return pd.DataFrame({"Avoided Demand Charge": rows})

    def drill_down_dfs(self, results: pd.DataFrame, dt: float
                       ) -> Dict[str, pd.DataFrame]:
        return {"demand_charges": self.engine.demand_charges_table()}
