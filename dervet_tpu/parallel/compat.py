"""jax version compatibility for the parallel modules.

The sharded solvers target the jax >= 0.6 API (top-level ``jax.shard_map``
with ``check_vma``). On older jax the same entry point lives in
``jax.experimental.shard_map`` and the varying-manual-axes checker is the
replication checker ``check_rep`` — which has no rule for ``while_loop``,
present in every PDHG chunk, so it must be disabled there.
"""
import jax

try:                                    # jax >= 0.6 top-level alias
    shard_map = jax.shard_map
except AttributeError:                  # jax < 0.6: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False, **kw)
