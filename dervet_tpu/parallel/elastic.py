"""Elastic multi-device dispatch: a mesh-wide structure-group scheduler.

The PR-3/5 dispatch drives ONE device group: on a multi-device mesh every
batched group rides a single ``shard_map`` program, so the round is a
SERIAL sequence of mesh-wide solves — 7 of 8 devices idle through every
group's host round trips, escalation rungs, and certification.  The
elastic scheduler converts that single global pipeline into N concurrent
per-device pipelines under one round:

* **Placement** — each structure group is assigned to a device by
  estimated cost (window count x horizon x a rolling per-structure
  iteration baseline fed back from the solve ledger), greedy
  longest-processing-time onto the least-loaded queue.  A structure that
  already has a compiled solver on some device is STICKY to that device
  (cache affinity beats balance: re-placing a warm structure would pay a
  fresh per-device XLA compile and break the hot service's zero-compile
  steady state).
* **Per-device in-flight rounds** — each device gets its own worker
  thread, solver-cache shard (``SolverCache.shard_for``: device-committed
  operator constants, per-device compiled programs, the warm-start
  solution memory stays SHARED), and staged-upload pipeline (the worker
  enqueues the next queued group's ``device_put`` onto its device before
  blocking in the current solve — the PR-3 overlap machinery, per device
  instead of global).
* **Work stealing** — a device that drains its queue while another still
  has PENDING groups steals the victim's tail group.  Re-placement is
  safe because structure groups are independent window LPs; the steal is
  recorded in the ledger (``stolen`` on the group entry, the steal list
  in ``solve_ledger.elastic``) and its data re-stages on the thief.

Safety: per-device solves are single-device vmap programs (no
collectives), so concurrent launches from worker threads cannot abort
the runtime the way two interleaved ``shard_map`` programs do — and
every group runs the SAME program whatever the mesh size, so elastic
results are BYTE-IDENTICAL across 1/2/8-device schedules, placements,
and steals (asserted in tests/test_elastic.py, gated in bench.py's
``serving_elastic`` leg).  The legacy sharded scheduler's bits depend
on the visible device count (per-device batch width changes the
dense-op XLA reduction order), so against it agreement is at
certification tolerance.

Kill switch: ``DERVET_TPU_ELASTIC=0`` restores the serial global
pipeline; ``DERVET_TPU_ELASTIC_DEVICES=N`` bounds the scheduler to the
first N devices (N=1 is allowed — a single-worker elastic round, used by
the byte-identity drills).
"""
from __future__ import annotations

import collections
import os
import queue as _queue
import threading
import time
from typing import Callable, Dict, List, Optional

ELASTIC_ENV = "DERVET_TPU_ELASTIC"
ELASTIC_DEVICES_ENV = "DERVET_TPU_ELASTIC_DEVICES"

# cost baseline for a structure the ledger has not measured yet: a
# mid-range PDLP iteration count (BENCH_r05 p50 1664, warm service 0 —
# the absolute value only matters relative to other unmeasured keys)
DEFAULT_ITERS_BASELINE = 512.0


def elastic_enabled() -> bool:
    """Elastic-scheduler kill switch (``DERVET_TPU_ELASTIC=0`` off)."""
    return os.environ.get(ELASTIC_ENV, "1").strip().lower() \
        not in ("0", "false", "off")


def device_limit() -> Optional[int]:
    raw = os.environ.get(ELASTIC_DEVICES_ENV, "").strip()
    if not raw:
        return None
    try:
        n = int(raw)
    except ValueError:
        return None
    return n if n >= 1 else None


def elastic_devices(backend: str) -> Optional[list]:
    """The device set an elastic round may schedule over, or None when
    the elastic path is off for this dispatch: cpu backend (no devices),
    kill switch, or a single visible device with no explicit limit (one
    device has nothing to schedule across — the plain pipeline is the
    cheaper identical path)."""
    if backend == "cpu" or not elastic_enabled():
        return None
    import jax
    devs = list(jax.devices())
    limit = device_limit()
    if limit is not None:
        devs = devs[:limit]
    elif len(devs) < 2:
        return None
    return devs


def estimate_group_cost(key, items, cache=None) -> float:
    """Placement cost of a structure group: window count x horizon x the
    structure's rolling iteration baseline.  The baseline comes from the
    solve ledger's feedback into the cache (``SolverCache.note_iters``,
    an EWMA of each structure's measured iters p50) or, for a warm
    service, the solution memory's cold baseline; unmeasured structures
    fall back to a flat constant so a cold round degenerates to
    windows-x-horizon LPT — still the right relative order."""
    n = len(items)
    T = getattr(items[0][1], "T", None) or 1
    baseline = None
    if cache is not None:
        hint = getattr(cache, "iters_hint", None)
        if hint is not None:
            baseline = hint(key)
        memory = getattr(cache, "memory", None)
        if baseline is None and memory is not None:
            baseline = memory.cold_p50(key)
    return float(n) * float(T) * float(baseline or DEFAULT_ITERS_BASELINE)


class GroupTask:
    """One schedulable structure group."""
    __slots__ = ("key", "items", "cost", "home", "device_index", "stolen",
                 "staged", "staged_device", "seq")

    def __init__(self, key, items, cost: float, home: int, seq: int = 0):
        self.key = key
        self.items = items
        self.cost = float(cost)
        self.home = home               # placement decision
        self.device_index = home       # where it actually solved
        self.stolen = False
        self.staged = None             # StagedGroupData (or None)
        self.staged_device = None      # device index the staging targeted
        # submission sequence number: the dispatch thread scatters
        # results in THIS order (not completion order), so the output
        # surface — CSV row order follows apply order — is deterministic
        # and identical to the serial path's
        self.seq = seq


class ElasticScheduler:
    """Per-device queues + workers with cost placement and work stealing.

    Protocol: construct, ``start(solve_fn, stage_fn)``, ``submit`` each
    group (may interleave with completions), ``close_submissions()``,
    then drain ``completions()`` on the dispatch thread; ``shutdown()``
    in a finally block.  ``solve_fn(device, device_index, task)`` runs on
    the worker thread and returns the value handed back through
    ``completions()``; ``stage_fn(device, task)`` returns the task's
    staged upload for that device (called off the queue lock)."""

    def __init__(self, devices: List):
        self.devices = list(devices)
        n = len(self.devices)
        self._queues = [collections.deque() for _ in range(n)]
        # OUTSTANDING cost per device: queued + in-flight (decremented
        # only when the group completes) — placement must see a device
        # that is mid-solve as loaded, or every early group piles onto
        # device 0 before any worker reports back
        self._qcost = [0.0] * n
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._done: _queue.Queue = _queue.Queue()
        self._stop = threading.Event()
        self._closed = False
        # which workers are mid-solve: stealing is only legitimate from
        # a BUSY device (an idle victim would pop its own queue head
        # immediately — "stealing" from it just moves the group off its
        # warm compiled-program shard for nothing, observed as phantom
        # steals + spurious per-device compiles at round start)
        self._inflight = [False] * n
        self._submitted = 0
        self._completed = 0
        self._threads: List[threading.Thread] = []
        self._t0: Optional[float] = None
        self._wall = 0.0
        # observables
        self.busy_s = [0.0] * n
        self.groups = [0] * n
        self.windows = [0] * n
        self.placed_cost = [0.0] * n
        self.steals: List[Dict] = []
        self.steals_in = [0] * n
        self.steals_out = [0] * n

    # -- placement ------------------------------------------------------
    def submit(self, key, items, cost: float,
               affinity: Optional[int] = None) -> GroupTask:
        """Place one group: cache affinity first (a device that already
        compiled this structure keeps it), else least-loaded by queued
        cost (greedy LPT — callers submit in discovery order, and the
        rolling cost estimates keep the queues balanced)."""
        with self._lock:
            if affinity is not None and 0 <= affinity < len(self.devices):
                d = affinity
            else:
                d = min(range(len(self.devices)),
                        key=lambda i: self._qcost[i])
            task = GroupTask(key, items, cost, d, seq=self._submitted)
            self._queues[d].append(task)
            self._qcost[d] += task.cost
            self._submitted += 1
            self.placed_cost[d] += task.cost
            self._cond.notify_all()
        return task

    def close_submissions(self) -> None:
        with self._lock:
            self._closed = True
            self._cond.notify_all()

    # -- worker side ----------------------------------------------------
    def _steal_victim(self, idx: int) -> Optional[int]:
        """The device with the most outstanding cost among those that
        are BUSY and still have QUEUED groups (in-flight work cannot be
        stolen; an idle device serves its own queue) — None when there
        is nothing legitimate to steal."""
        best, best_cost = None, 0.0
        for j, q in enumerate(self._queues):
            if j != idx and q and self._inflight[j] \
                    and self._qcost[j] > best_cost:
                best, best_cost = j, self._qcost[j]
        return best

    def _next(self, idx: int) -> Optional[GroupTask]:
        with self._lock:
            while True:
                if self._stop.is_set():
                    return None
                if self._queues[idx]:
                    self._inflight[idx] = True
                    return self._queues[idx].popleft()
                victim = self._steal_victim(idx)
                if victim is not None:
                    task = self._queues[victim].pop()   # tail group
                    # the outstanding cost moves with the group
                    self._qcost[victim] -= task.cost
                    self._qcost[idx] += task.cost
                    self._inflight[idx] = True
                    task.stolen = True
                    task.device_index = idx
                    self.steals_in[idx] += 1
                    self.steals_out[victim] += 1
                    self.steals.append({
                        "from_device": victim, "to_device": idx,
                        "windows": len(task.items),
                        "cost": round(task.cost, 1)})
                    return task
                if self._closed:
                    return None
                self._cond.wait(timeout=0.1)

    def _peek(self, idx: int) -> Optional[GroupTask]:
        with self._lock:
            return self._queues[idx][0] if self._queues[idx] else None

    def _commit_stage(self, idx: int, task: GroupTask, staged) -> None:
        """Attach a prestaged upload to a still-QUEUED task.  Committed
        under the scheduler lock and only while the task remains on this
        device's own queue: popping (own or steal) happens under the
        same lock, so a task that has left the queue can never receive a
        late commit — without this, a thief could read buffers committed
        to the victim's device mid-overwrite."""
        with self._lock:
            if task in self._queues[idx]:
                task.staged = staged
                task.staged_device = idx

    def _worker(self, idx: int, solve_fn, stage_fn) -> None:
        device = self.devices[idx]
        while True:
            task = self._next(idx)
            if task is None:
                return
            # from here the task is exclusively this worker's: pops are
            # serialized under the lock and prestage commits require
            # queue membership, so no other thread writes it again
            task.device_index = idx
            t0 = time.perf_counter()
            try:
                if stage_fn is not None and (task.staged is None
                                             or task.staged_device != idx):
                    # stolen (or never-staged) group: its upload targets
                    # THIS device now
                    task.staged = stage_fn(device, task)
                    task.staged_device = idx
                # per-device staged-upload pipeline: enqueue the NEXT
                # queued group's async device_put before blocking in this
                # group's solve, so the transfer rides under the solve
                # (a thief re-stages if it takes the group first — the
                # wasted upload is bounded by one group per device)
                nxt = self._peek(idx)
                if stage_fn is not None and nxt is not None \
                        and nxt.staged is None:
                    self._commit_stage(idx, nxt, stage_fn(device, nxt))
                result = solve_fn(device, idx, task)
                err = None
            except BaseException as e:    # propagated on the dispatch thread
                result, err = None, e
            dt = time.perf_counter() - t0
            with self._lock:
                self.busy_s[idx] += dt
                self.groups[idx] += 1
                self.windows[idx] += len(task.items)
                self._qcost[idx] -= task.cost   # outstanding -> done
                self._inflight[idx] = False
                # a queue may have refilled behind a busy worker — wake
                # potential thieves now that stealing from it is legal
                self._cond.notify_all()
            self._done.put((task, result, err))

    # -- dispatch-thread side ------------------------------------------
    def start(self, solve_fn: Callable, stage_fn: Optional[Callable] = None
              ) -> "ElasticScheduler":
        self._t0 = time.perf_counter()
        for i in range(len(self.devices)):
            t = threading.Thread(target=self._worker,
                                 args=(i, solve_fn, stage_fn),
                                 name=f"dervet-elastic-d{i}", daemon=True)
            self._threads.append(t)
            t.start()
        return self

    def completions(self):
        """Yield ``(task, result, error)`` for every submitted group, in
        completion order; returns when all submitted groups completed
        (requires ``close_submissions`` to have been called by then).
        Raising out of the consuming loop (scatter errors, preemption)
        is safe — ``shutdown()`` stops the workers."""
        while True:
            with self._lock:
                if self._closed and self._completed >= self._submitted:
                    return
            try:
                item = self._done.get(timeout=0.5)
            except _queue.Empty:
                if not any(t.is_alive() for t in self._threads):
                    with self._lock:
                        drained = (self._completed >= self._submitted
                                   and self._closed)
                    if drained:
                        return
                    raise RuntimeError(
                        "elastic scheduler: all workers exited with "
                        f"{self._submitted - self._completed} group(s) "
                        "unaccounted")
                continue
            with self._lock:
                self._completed += 1
            self._wall = time.perf_counter() - self._t0
            yield item

    def shutdown(self) -> None:
        """Stop the workers (current solves finish; queued groups are
        abandoned — the preemption/error path) and join them."""
        self._stop.set()
        with self._lock:
            self._cond.notify_all()
        for t in self._threads:
            t.join()
        if self._t0 is not None and not self._wall:
            self._wall = time.perf_counter() - self._t0

    # -- observability --------------------------------------------------
    def stats(self) -> Dict:
        """The round's elastic observables for ``solve_ledger.elastic``:
        per-device occupancy (busy wall over round wall — the >= 70%
        serving gate), group/window/steal counts, placement cost."""
        wall = self._wall or (time.perf_counter() - self._t0
                              if self._t0 else 0.0)
        devices = {}
        for i in range(len(self.devices)):
            devices[str(i)] = {
                "groups": self.groups[i],
                "windows": self.windows[i],
                "busy_s": round(self.busy_s[i], 4),
                "occupancy": round(self.busy_s[i] / wall, 4) if wall else 0.0,
                "steals_in": self.steals_in[i],
                "steals_out": self.steals_out[i],
                "placed_cost": round(self.placed_cost[i], 1),
            }
        return {
            "n_devices": len(self.devices),
            "round_wall_s": round(wall, 4),
            "devices": devices,
            "n_steals": len(self.steals),
            "steals": self.steals[:64],
            "devices_with_groups": sum(1 for g in self.groups if g),
        }
