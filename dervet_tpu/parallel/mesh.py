"""Multi-chip execution: scenario-axis sharding of the batched LP solve.

The reference is a single-process CPU program (SURVEY.md §2.10); its only
"parallelism" is a Python for-loop over sensitivity cases (reference:
dervet/DERVET.py:75-83).  The TPU-native scale-out axis is the scenario
batch — sensitivity cases x sizing sweeps x Monte-Carlo draws x same-length
windows — sharded over a 1-D device mesh with ``jax.shard_map``:

* problem *structure* (the ELL/dense K tables, Ruiz scalings, step size) is
  replicated on every chip — it is identical across the batch;
* per-scenario data ``c, q, l, u`` is sharded on the leading axis; each chip
  runs the vmapped PDHG solve on its local shard (compute rides the MXU,
  zero inter-chip traffic in the hot loop);
* the only collectives are cheap ``psum`` reductions of convergence
  statistics — they ride ICI and cost nothing relative to the solve.

This layout is the "pick a mesh, annotate shardings, let XLA insert
collectives" recipe: dispatch scenarios are embarrassingly parallel, so the
right multi-chip program keeps them independent and reduces only scalars.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.pdhg import CompiledLPSolver, PDHGResult
from .compat import shard_map

AXIS = "scenario"


class ShardedStats(NamedTuple):
    """Globally-reduced (psum) solve statistics."""
    n_converged: jax.Array   # total converged scenarios across the mesh
    max_iters: jax.Array     # worst-case iteration count across the mesh
    max_prim_res: jax.Array  # worst primal residual across the mesh


def _warmup_lp():
    """A tiny battery-shaped LP (SOE recursion + box + prices — the same
    block structure every dispatch window emits) for the per-device
    warm-up solve.  T=8 keeps it milliseconds on any backend."""
    from ..ops.lp import LPBuilder
    T = 8
    b = LPBuilder()
    ch = b.var("ch", T, 0.0, 10.0)
    dis = b.var("dis", T, 0.0, 10.0)
    ene = b.var("ene", T, 0.0, 40.0)
    D = np.eye(T) - np.eye(T, k=-1)
    rhs = np.zeros(T)
    rhs[0] = 20.0
    b.add_rows("soe", [(ene, D), (ch, -0.85), (dis, 1.0)], "eq", rhs)
    price = np.linspace(0.01, 0.08, T)
    b.add_cost(ch, price)
    b.add_cost(dis, -price)
    return b.build()


def warmup_devices(per_device_solve: bool = True, devices=None) -> dict:
    """Pay backend/device initialization up front (serving layer): the
    first JAX touch of a process initializes the platform, allocates the
    transfer arenas, and compiles a trivial program — tens of
    milliseconds to seconds that would otherwise land inside the FIRST
    request's latency.  A :class:`~dervet_tpu.service.server.
    ScenarioService` calls this at ``start()`` so admission begins on a
    warm device.

    ``per_device_solve`` additionally runs one TINY bucket-shaped (batch
    8, the smallest compaction bucket) PDHG solve on EVERY device, not
    just the default one: the elastic scheduler places groups across the
    whole mesh, and a device that has never executed anything pays its
    first-touch cost (allocator arenas, transfer paths, executable
    build) inside the first request otherwise.  Per-device warm-up
    timings ride the returned dict (``warmup_s`` keyed by device index)
    so a sick/slow device is visible at service start.

    ``devices`` restricts the per-device warm solves to that subset
    (the service passes its elastic device set — warming a device the
    scheduler will never place a group on is wasted compile time).

    Returns the device inventory for the service's metrics surface."""
    all_devs = jax.devices()
    devs = list(devices) if devices is not None else all_devs
    x = jax.device_put(jnp.zeros(8, jnp.float32))
    jax.jit(lambda a: a + 1.0)(x).block_until_ready()
    info = {"n_devices": len(all_devs),
            "platform": all_devs[0].platform,
            "device_kind": all_devs[0].device_kind}
    if per_device_solve:
        import concurrent.futures as cf
        import time
        from ..ops.pdhg import CompiledLPSolver
        lp = _warmup_lp()
        t_all = time.perf_counter()
        base = CompiledLPSolver(lp, device=devs[0])

        def _warm_one(i, d):
            t0 = time.perf_counter()
            solver = base if i == 0 else base.to_device(d)
            C = np.broadcast_to(lp.c, (8, lp.n))    # bucket-shaped batch
            res = solver.solve(c=np.ascontiguousarray(C))
            jax.block_until_ready(res.x)
            return str(i), round(time.perf_counter() - t0, 4)

        # warm the devices CONCURRENTLY: the cost is per-device XLA
        # compiles of the tiny program, which overlap across threads
        # exactly like the dispatch pipeline's compile overlap — serial
        # warm-up would pay n_devices x the compile wall for nothing
        with cf.ThreadPoolExecutor(max_workers=min(8, len(devs))) as pool:
            timings = dict(pool.map(lambda a: _warm_one(*a),
                                    enumerate(devs)))
        info["warmup_s"] = timings
        info["warmup_total_s"] = round(time.perf_counter() - t_all, 4)
    return info


def scenario_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the scenario/batch axis."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)} "
                f"(set XLA_FLAGS=--xla_force_host_platform_device_count=N "
                f"for CPU testing)")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (AXIS,))


def solve_batch_sharded(solver: CompiledLPSolver, mesh: Mesh,
                        c=None, q=None, l=None, u=None, stats=None,
                        x0=None, y0=None):
    """Solve a batch of LP instances sharded over ``mesh``.

    Any of ``c/q/l/u`` may be 1-D (shared, replicated) or 2-D batched on the
    leading axis.  The batch is padded up to a multiple of the mesh size
    (padding rows replicate the last row) and trimmed from the result;
    padding rows are masked out of the psum'd statistics.  ``x0``/``y0``
    (optional UNSCALED warm-start seeds, batched like the data) route
    through the seeded init program — sharded on the same axis.

    Returns ``(PDHGResult, ShardedStats)`` with result arrays batched on the
    original (un-padded) leading axis.

    Like ``CompiledLPSolver._drive``, falls back to the XLA scan path if
    the fused Pallas chunk kernel fails to COMPILE on this backend (the
    vmapped stages fire the same custom-vmap rule inside ``shard_map``).
    """
    import dataclasses

    from ..ops.pdhg import disable_pallas_runtime, is_pallas_compile_failure
    # same per-solver serialization as CompiledLPSolver._drive: the
    # fallback below mutates solver.opts and rebuilds the jits, which
    # must not interleave with another thread's solve on this solver
    # (ADVICE r4 / review r5)
    with solver._solve_lock:
        try:
            return _solve_batch_sharded_inner(solver, mesh, c, q, l, u,
                                              stats, x0=x0, y0=y0)
        except Exception as e:
            from ..ops import pallas_chunk
            kernel_in_play = (solver.opts.pallas_chunk
                              and pallas_chunk.supports(
                                  solver.op, solver.opts.dtype,
                                  solver.opts.precision,
                                  ignore_runtime_disabled=True,
                                  variant=solver.variant))
            if not (kernel_in_play and is_pallas_compile_failure(e)):
                raise
            disable_pallas_runtime(e)
            solver.opts = dataclasses.replace(solver.opts,
                                              pallas_chunk=False)
            solver._make_jits()
            # fresh jits = fresh XLA programs: reset compile-event tracking
            solver._exec_shapes.clear()
            return _solve_batch_sharded_inner(solver, mesh, c, q, l, u,
                                              stats, x0=x0, y0=y0)


def _solve_batch_sharded_inner(solver: CompiledLPSolver, mesh: Mesh,
                               c=None, q=None, l=None, u=None, stats=None,
                               x0=None, y0=None):
    import time

    from ..ops.pdhg import SolveStats
    # same per-solve traffic accounting as the single-device driver, so
    # the dispatch solve ledger stays populated on a multi-chip mesh.
    # Callers that must not race pass their OWN stats; last_stats is
    # assigned under _solve_lock (we are inside it here).
    if stats is None:
        stats = SolveStats()
    solver.last_stats = stats
    c, q, l, u = solver._data(c, q, l, u, stats)
    sizes = {arr.shape[0] for arr in (c, q, l, u) if arr.ndim == 2}
    if not sizes:
        raise ValueError("solve_batch_sharded needs at least one batched input")
    if len(sizes) > 1:
        raise ValueError(f"inconsistent batch sizes: {sorted(sizes)}")
    B = sizes.pop()
    c, q, l, u = solver.batch_data(B, c, q, l, u)
    x0, y0 = solver._seed_data(x0, y0, stats)
    if x0 is not None:
        x0 = jnp.broadcast_to(x0, (B, solver.lp.n)) if x0.ndim == 1 else x0
        y0 = jnp.broadcast_to(y0, (B, solver.lp.m)) if y0.ndim == 1 else y0

    n_dev = mesh.devices.size
    B_pad = ((B + n_dev - 1) // n_dev) * n_dev
    if B_pad != B:
        c, q, l, u = (jnp.pad(a, [(0, B_pad - B)] + [(0, 0)] * (a.ndim - 1),
                              mode="edge") for a in (c, q, l, u))
        if x0 is not None:
            x0, y0 = (jnp.pad(a, [(0, B_pad - B), (0, 0)], mode="edge")
                      for a in (x0, y0))

    valid = (jnp.arange(B_pad) < B).astype(jnp.int32)

    # the same host-chunked init/chunk/finalize driver as the single-host
    # path, each stage shard_map-ed over the scenario axis — a sharded
    # solve is still a sequence of bounded device steps (watchdog-safe,
    # chunk-level progress), not one multi-minute XLA program
    vinit = jax.vmap(solver._solve.init_state,
                     in_axes=(None, 0, 0, 0, 0, None, None))
    vinit_seed = jax.vmap(solver._solve.init_state,
                          in_axes=(None, 0, 0, 0, 0, None, None, 0, 0))
    vchunk = jax.vmap(solver._solve.run_chunk,
                      in_axes=(None, 0, 0, 0, 0, None, None, None, 0, None))
    vfin = jax.vmap(solver._solve.finalize,
                    in_axes=(None, 0, 0, 0, 0, None, None, 0))

    def local_init(c, q, l, u):
        return vinit(solver.op, c, q, l, u, solver.dr, solver.dc)

    def local_init_seed(c, q, l, u, x0, y0):
        return vinit_seed(solver.op, c, q, l, u, solver.dr, solver.dc,
                          x0, y0)

    def local_chunk(c, q, l, u, state, limit):
        return vchunk(solver.op, c, q, l, u, solver.dr, solver.dc,
                      solver.eta, state, limit)

    def local_fin(c, q, l, u, state, valid):
        res = vfin(solver.op, c, q, l, u, solver.dr, solver.dc, state)
        stats = ShardedStats(
            n_converged=jax.lax.psum(
                jnp.sum(res.converged.astype(jnp.int32) * valid), AXIS),
            max_iters=jax.lax.pmax(jnp.max(res.iters * valid), AXIS),
            max_prim_res=jax.lax.pmax(
                jnp.max(jnp.where(valid == 1, res.prim_res, 0.0)), AXIS),
        )
        return res, stats

    res_specs = PDHGResult(x=P(AXIS), y=P(AXIS), obj=P(AXIS),
                           converged=P(AXIS), iters=P(AXIS),
                           prim_res=P(AXIS), gap=P(AXIS), status=P(AXIS),
                           restarts=P(AXIS))
    sh_init = jax.jit(shard_map(
        local_init, mesh=mesh, in_specs=(P(AXIS),) * 4, out_specs=P(AXIS)))
    sh_init_seed = jax.jit(shard_map(
        local_init_seed, mesh=mesh, in_specs=(P(AXIS),) * 6,
        out_specs=P(AXIS)))
    from ..ops.pdhg import pallas_compiler_options
    sh_chunk = jax.jit(shard_map(
        local_chunk, mesh=mesh,
        in_specs=(P(AXIS),) * 4 + (P(AXIS), P()), out_specs=P(AXIS)),
        compiler_options=pallas_compiler_options(solver.opts, solver.op))
    sh_fin = jax.jit(shard_map(
        local_fin, mesh=mesh, in_specs=(P(AXIS),) * 4 + (P(AXIS), P(AXIS)),
        out_specs=(res_specs, ShardedStats(n_converged=P(), max_iters=P(),
                                           max_prim_res=P()))))

    opts = solver.opts
    if x0 is not None:
        solver._note_exec("sh_init_seeded", c.shape, stats)
        state = sh_init_seed(c, q, l, u, x0, y0)
    else:
        solver._note_exec("sh_init", c.shape, stats)
        state = sh_init(c, q, l, u)
    stats.dispatches += 1
    total = 0
    while True:
        limit = jnp.asarray(min(total + opts.chunk_iters, opts.max_iters),
                            jnp.int32)
        solver._note_exec("sh_chunk", c.shape, stats)
        state = sh_chunk(c, q, l, u, state, limit)
        t0 = time.perf_counter()
        total = int(np.asarray(state.total).max())
        active = ~(np.asarray(state.converged) | np.asarray(state.infeasible))
        stats.dispatches += 1
        stats.chunks += 1
        stats.readbacks += 1
        stats.sync_wait_s += time.perf_counter() - t0
        if not active.any() or total >= opts.max_iters:
            break
    solver._note_exec("sh_fin", c.shape, stats)
    res, sh_stats = sh_fin(c, q, l, u, state, valid)
    stats.dispatches += 1
    if B_pad != B:
        res = PDHGResult(*(a[:B] for a in res))
    return res, sh_stats
