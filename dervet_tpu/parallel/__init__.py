from .mesh import scenario_mesh, solve_batch_sharded  # noqa: F401
