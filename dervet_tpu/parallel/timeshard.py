"""Time-axis (row) sharding of ONE large dispatch LP over a device mesh.

The scenario axis (``parallel/mesh.py``) is the workhorse scale-out axis;
this module covers the orthogonal case SURVEY.md §2.10 commits to under
TP/SP: a *single* LP too long for comfortable single-chip iteration —
e.g. a 5-minute-resolution year window (T=105,120 steps, n≈420k vars) —
sharded over the mesh the way sequence parallelism shards a long context.

Dispatch-LP rows are time-indexed (SOE evolution, power balance, market
headroom per step), so sharding constraint ROWS shards the year:

* each device owns a contiguous row block (its slice of the year) as an
  ELLPACK table, plus that block's transpose, dual slice ``y``, row
  scaling ``d_r`` and rhs ``q``;
* the primal ``x`` (and everything n-dimensional) is replicated — for a
  dispatch LP n ≈ 4T floats, a few MB at 5-min resolution: cheap to
  replicate, so K@x needs NO communication at all;
* the only collectives per iteration are one ``psum`` of the partial
  gradients K^T@y (the all-to-all of this "sequence parallelism") and
  scalar ``psum``s for norms/termination — both ride ICI.

The PDHG algorithm itself is the SAME code as the single-chip solver:
``ops/pdhg._make_solver(axis=...)`` swaps every row-space reduction for a
psum (see ShardRowOp there), so restarts, primal-weight updates,
infeasibility certificates and termination behave identically — a
sharded solve returns bit-comparable results to the unsharded one up to
f32 reduction order.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.lp import LP
from .compat import shard_map
from ..ops.pdhg import (EllOp, PDHGOptions, PDHGResult, ShardRowOp, _State,
                        _csr_to_ell, _make_solver, op_matvec, op_rmatvec,
                        ruiz_scaling)

AXIS = "time"


def time_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the time(row) axis."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)} "
                f"(set XLA_FLAGS=--xla_force_host_platform_device_count=N "
                f"for CPU testing)")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (AXIS,))


class TimeShardedLPSolver:
    """Row-sharded PDHG for one large LP on a 1-D mesh.

    Usage::

        mesh = time_mesh(8)
        res = TimeShardedLPSolver(lp, mesh).solve()

    ``res`` is a plain :class:`PDHGResult` for the ORIGINAL (unpadded)
    problem. Dense-column splitting is not used on this path (size/epigraph
    variables appear in sizing LPs, which are small and batch on the
    scenario axis instead); rows are zero-padded to a device multiple.
    """

    def __init__(self, lp: LP, mesh: Mesh, opts: Optional[PDHGOptions] = None):
        self.opts = opts or PDHGOptions()
        self.lp = lp
        self.mesh = mesh
        dtype = self.opts.dtype
        D = int(mesh.devices.size)
        m, n = lp.m, lp.n

        d_r, d_c = ruiz_scaling(lp.K, self.opts.ruiz_iters)
        Kh = lp.K.multiply(d_r[:, None]).multiply(d_c[None, :]).tocsr()

        # pad rows to a device multiple with zero rows (q=0, inequality:
        # the padded dual stays pinned at 0)
        m_loc = (m + D - 1) // D
        m_pad = m_loc * D
        self.m_loc, self.m_pad = m_loc, m_pad

        # per-block CSR slices, sliced ONCE per block; widths from the
        # indptr so only one block's ELL tables are alive at a time on
        # top of the stacked output arrays
        blocks, blocks_t = [], []
        KhT = Kh.T.tocsr()  # (n, m)
        for b in range(D):
            lo, hi = b * m_loc, min((b + 1) * m_loc, m)
            blocks.append(Kh[lo:hi] if hi > lo else Kh[:0])
            # transpose block: (n, m_local), column ids LOCAL to the block
            blocks_t.append(KhT[:, lo:hi].tocsr())

        def _max_width(csr):
            counts = np.diff(csr.indptr)
            return int(counts.max()) if counts.size else 0

        k = max(max(_max_width(b) for b in blocks), 1)
        kt = max(max(_max_width(b) for b in blocks_t), 1)

        data = np.zeros((m_pad, k), np.float64)
        cols = np.zeros((m_pad, k), np.int32)
        data_t = np.zeros((D * n, kt), np.float64)
        cols_t = np.zeros((D * n, kt), np.int32)
        for b in range(D):
            d, c = _csr_to_ell(blocks[b])
            data[b * m_loc:b * m_loc + d.shape[0], :d.shape[1]] = d
            cols[b * m_loc:b * m_loc + d.shape[0], :c.shape[1]] = c
            dt, ct = _csr_to_ell(blocks_t[b])
            data_t[b * n:(b + 1) * n, :dt.shape[1]] = dt
            cols_t[b * n:(b + 1) * n, :ct.shape[1]] = ct

        eq_mask = np.zeros(m_pad, bool)
        eq_mask[:lp.n_eq] = True

        empty_idx = jnp.zeros((0,), jnp.int32)
        empty_blk = jnp.zeros((m_pad, 0), dtype)
        self.op = ShardRowOp(
            inner=EllOp(data=jnp.asarray(data, dtype),
                        cols=jnp.asarray(cols),
                        data_t=jnp.asarray(data_t, dtype),
                        cols_t=jnp.asarray(cols_t),
                        dense_idx=empty_idx, dense_blk=empty_blk),
            eq_mask=jnp.asarray(eq_mask))
        self.dr = jnp.asarray(np.pad(d_r, (0, m_pad - m),
                                     constant_values=1.0), dtype)
        self.dc = jnp.asarray(d_c, dtype)
        self.q = jnp.asarray(np.pad(lp.q, (0, m_pad - m)), dtype)
        self.c = jnp.asarray(lp.c, dtype)
        self.l = jnp.asarray(lp.l, dtype)
        self.u = jnp.asarray(lp.u, dtype)

        solve = _make_solver(self.opts, m_loc, n, lp.n_eq, axis=AXIS)

        # sharding specs: row-space sharded, x-space + scalars replicated
        op_spec = ShardRowOp(
            inner=EllOp(data=P(AXIS), cols=P(AXIS), data_t=P(AXIS),
                        cols_t=P(AXIS), dense_idx=P(), dense_blk=P(AXIS)),
            eq_mask=P(AXIS))

        # step size via SHARDED power iteration — the whole point of this
        # path is that no single device ever holds the full operator
        prec = self.opts.precision
        n_pow = self.opts.power_iters

        def _power(op, v0):
            def piter(v, _):
                w = jax.lax.psum(
                    op_rmatvec(op.inner, op_matvec(op.inner, v, prec), prec),
                    AXIS)
                nw = jnp.linalg.norm(w)
                return w / jnp.maximum(nw, 1e-30), nw

            _, norms = jax.lax.scan(piter, v0, None, length=n_pow)
            return norms[-1]

        v0 = np.random.default_rng(0).standard_normal(n)
        v0 = jnp.asarray(v0 / np.linalg.norm(v0), dtype)
        sig2 = jax.jit(shard_map(
            _power, mesh=mesh, in_specs=(op_spec, P()), out_specs=P(),
            check_vma=False))(self.op, v0)
        sigma_max = float(jnp.sqrt(sig2))
        self.eta = jnp.asarray(
            self.opts.step_size_safety / max(sigma_max, 1e-12), dtype)
        row, rep = P(AXIS), P()
        state_spec = _State(
            x=rep, y=row, x_sum=rep, y_sum=row, inner=rep, total=rep,
            omega=rep, x_restart=rep, y_restart=row, mu_restart=rep,
            mu_prev=rep, converged=rep, done_x=rep, done_y=row,
            iters_at_conv=rep, infeas_streak=rep, infeasible=rep,
            restarts=rep, cadence=rep)
        res_spec = PDHGResult(x=rep, y=row, obj=rep, converged=rep,
                              iters=rep, prim_res=rep, gap=rep, status=rep,
                              restarts=rep)
        data_specs = (op_spec, rep, row, rep, rep, row, rep)

        # every row-space reduction inside is an explicit psum, so outputs
        # declared replicated ARE replicated; vma tracking cannot see that
        # through the while_loop carries, hence check_vma=False
        self._init = jax.jit(shard_map(
            solve.init_state, mesh=mesh, in_specs=data_specs,
            out_specs=state_spec, check_vma=False))
        self._chunk = jax.jit(shard_map(
            solve.run_chunk, mesh=mesh,
            in_specs=data_specs + (rep, state_spec, rep),
            out_specs=state_spec, check_vma=False))
        self._fin = jax.jit(shard_map(
            solve.finalize, mesh=mesh, in_specs=data_specs + (state_spec,),
            out_specs=res_spec, check_vma=False))

    def solve(self) -> PDHGResult:
        """Host-chunked sharded solve (same driver shape as the single-chip
        CompiledLPSolver._drive)."""
        from ..ops.pdhg import _status_scalars

        args = (self.op, self.c, self.q, self.l, self.u, self.dr, self.dc)
        state = self._init(*args)
        opts = self.opts
        total = 0
        while True:
            limit = np.int32(min(total + opts.chunk_iters, opts.max_iters))
            state = self._chunk(*args, self.eta, state, limit)
            # one fused readback per chunk (remote fetches cost ~100 ms
            # of latency each regardless of size)
            total, n_active, _cad = (int(v) for v in np.asarray(
                _status_scalars(state.total, state.converged,
                                state.infeasible, state.cadence)))
            if n_active == 0 or total >= opts.max_iters:
                break
        res = self._fin(*args, state)
        # trim padded dual rows back to the original problem
        return PDHGResult(x=res.x, y=res.y[:self.lp.m], obj=res.obj,
                          converged=res.converged, iters=res.iters,
                          prim_res=res.prim_res, gap=res.gap,
                          status=res.status, restarts=res.restarts)
