"""CI smoke: the warm-start subsystem on the cpu XLA backend, no chip.

Boots a :class:`~dervet_tpu.service.server.ScenarioService`, serves one
COLD request, then the identical request again WARM, and gates the
warm-start acceptance contract:

* >= 30% median iteration reduction on the warm pass (ledger
  ``iters p50`` cold vs seeded — exact-match substitution drives it to
  0);
* 100% of the warm pass's windows carry an accepted float64
  certificate (a warm start must never weaken the trust layer);
* ZERO compile events on the warm pass (the seeded program family is
  part of the cold round's warm-up, so a warm round compiles nothing);
* the warm pass's results are BYTE-IDENTICAL to the cold pass's across
  the full results-CSV surface (substitution re-verifies the stored
  solution in float64, then ships it verbatim).

Env knobs: SMOKE_CASES (default 2), SMOKE_MONTHS (default 1).
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    from dervet_tpu.benchlib import (synthetic_sensitivity_cases,
                                     validate_solve_ledger)
    from dervet_tpu.service import ScenarioService

    n_cases = int(os.environ.get("SMOKE_CASES", "2"))
    months = int(os.environ.get("SMOKE_MONTHS", "1"))
    cases = {i: c for i, c in enumerate(
        synthetic_sensitivity_cases(n_cases, months=months))}

    svc = ScenarioService(backend="jax", max_wait_s=0.0)
    svc.start()
    try:
        cold_res = svc.submit(cases, request_id="cold").result(timeout=600)
        cold_led = svc.last_round_ledger
        warm_res = svc.submit(cases, request_id="warm").result(timeout=600)
        warm_led = svc.last_round_ledger
        metrics = svc.metrics()
    finally:
        svc.drain()

    validate_solve_ledger(warm_led)
    cold_p50 = (cold_led.get("warm_start") or {}).get("iters_p50_cold")
    if cold_p50 is None:
        cold_p50 = cold_led["iters"]["p50"]
    warm = warm_led.get("warm_start") or {}
    warm_p50 = warm.get("iters_p50_seeded")
    n_windows = sum(len(inst.scenario.windows)
                    for inst in warm_res.instances.values())

    # gate 1: >= 30% median iteration reduction on the warm pass
    if warm.get("seeded", 0) != n_windows:
        raise AssertionError(
            f"warm pass seeded {warm.get('seeded')}/{n_windows} windows "
            f"(warm_start: {warm})")
    if warm_p50 is None or cold_p50 <= 0 or \
            warm_p50 > 0.7 * cold_p50:
        raise AssertionError(
            f"warm iters p50 {warm_p50} vs cold {cold_p50}: the >=30% "
            "median iteration-reduction gate failed")

    # gate 2: 100% certified on the warm pass
    cert = warm_res.run_health["certification"]
    if not cert["enabled"] or cert["windows_certified"] != n_windows \
            or cert["windows"]["rejected_final"]:
        raise AssertionError(f"warm pass not 100% certified: {cert}")

    # gate 3: zero compile events on the warm pass
    warm_compiles = int(warm_led["totals"]["compile_events"])
    if warm_compiles:
        raise AssertionError(
            f"warm pass compiled {warm_compiles} program(s) — the "
            "seeded program family must be part of the cold warm-up")

    # gate 4: byte-identical results-CSV surface, warm vs cold
    with tempfile.TemporaryDirectory() as td:
        cold_res.save_as_csv(Path(td) / "cold")
        warm_res.save_as_csv(Path(td) / "warm")
        names = sorted(p.name for p in (Path(td) / "cold").glob("*.csv"))
        if not names or names != sorted(
                p.name for p in (Path(td) / "warm").glob("*.csv")):
            raise AssertionError("cold/warm CSV surfaces differ in shape")
        for name in names:
            a = (Path(td) / "cold" / name).read_bytes()
            b = (Path(td) / "warm" / name).read_bytes()
            if a != b:
                raise AssertionError(
                    f"{name}: warm pass differs from the cold pass — "
                    "byte-identity gate failed")

    print(json.dumps({
        "smoke": "warmstart", "ok": True,
        "windows": n_windows,
        "iters_p50_cold": int(cold_p50),
        "iters_p50_warm": int(warm_p50),
        "reduction": round(1.0 - warm_p50 / cold_p50, 4),
        "substituted": warm.get("substituted"),
        "warm_compile_events": warm_compiles,
        "memory": metrics["warm_start"],
        "seeded_windows_total": metrics["rounds"]["seeded_windows"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
