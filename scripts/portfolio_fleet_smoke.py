"""CI smoke: fleet-sharded portfolio dual rounds under a real SIGKILL.

Boots a 2-replica fleet (real ``dervet-tpu serve`` subprocesses over
file spools, CPU backend), then solves ONE coupled portfolio whose dual
rounds are sharded ACROSS the fleet: each outer round ships two
``portfolio_shard`` requests (site cases + the round's dual-price
vector) through :class:`~dervet_tpu.service.router.FleetRouter.
submit_shards`, and one replica is SIGKILLed mid-loop.  The contract
under fire:

* **sticky shards** — before the kill, each shard index lands on the
  SAME replica round over round (per-shard affinity keys: that replica's
  compiled programs and ``dual_iterate`` hint table are warm for it),
  and the two shards are spread over both replicas;
* **re-route, 0 lost** — the dead replica's shard re-routes through the
  PR-10 exactly-once failover machinery (router failover/reroute
  counters nonzero), every subsequent round runs entirely on the
  survivor, and the dual loop never loses a site or a round;
* **gap reached** — the loop still converges to the spec tolerance
  within the outer budget;
* **100% certified** — every member site's final-iterate windows carry
  accepted float64 certificates and the portfolio certificate
  (coupling feasibility + Lagrangian gap) accepts.

Env knobs: SMOKE_SITES (default 16), SMOKE_HOURS (48), SMOKE_WINDOW
(24), SMOKE_SLOW_S (default 0.08 — per-solve injected delay so the
SIGKILL reliably lands while a round is in flight).
"""
from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_SITES = int(os.environ.get("SMOKE_SITES", "16"))
HOURS = int(os.environ.get("SMOKE_HOURS", "48"))
WINDOW = int(os.environ.get("SMOKE_WINDOW", "24"))
SLOW_S = os.environ.get("SMOKE_SLOW_S", "0.08")


def log(msg: str) -> None:
    print(f"portfolio-fleet-smoke: {msg}", file=sys.stderr, flush=True)


def main() -> int:
    import tempfile

    from dervet_tpu.ops.certify import validate_portfolio_certification
    from dervet_tpu.portfolio import (PortfolioSpec, solve_portfolio,
                                      validate_portfolio_section)
    from dervet_tpu.portfolio.service import synthetic_portfolio_members
    from dervet_tpu.service import FleetRouter, spawn_replica

    def members():
        return synthetic_portfolio_members(N_SITES, hours=HOURS,
                                           window=WINDOW, seed=0,
                                           pv_kw=9000.0)

    # binding cap from an unconstrained local probe (round 0 of a
    # 1-round solve IS the independent fleet solve)
    probe = solve_portfolio(
        PortfolioSpec(members=members(), export_cap_kw=1e9, max_outer=1),
        backend="cpu")
    cap = float(probe.aggregate["net_export"].max()) - 250.0 * N_SITES
    spec = PortfolioSpec(members=members(), export_cap_kw=cap,
                         gap_tol=1e-6, feas_tol=1e-7, max_outer=40,
                         shards=2)

    workdir = Path(tempfile.mkdtemp(prefix="pf-fleet-smoke-"))
    log(f"spooling under {workdir}")
    # every solve carries a small injected delay so a round is reliably
    # IN FLIGHT when the SIGKILL lands (the delay is outside the solver
    # — correctness untouched)
    env = {"DERVET_TPU_FAULT_SLOW": "all",
           "DERVET_TPU_FAULT_SLOW_S": SLOW_S}
    reps, logs = [], []
    for i in range(2):
        name = f"r{i}"
        logf = open(workdir / f"{name}.log", "w")
        logs.append(logf)
        reps.append(spawn_replica(
            workdir / name, name=name, backend="cpu", stdout=logf,
            stderr=logf, env=env,
            extra_args=["--memory-export-s", "0.5"]))
    router = FleetRouter(reps, fleet_dir=workdir / "router",
                         heartbeat_timeout_s=1.5, tick_s=0.05,
                         hedging=False).start()

    kill_state = {"victim": None, "killed_at_round": None}

    def on_round(k: int, result) -> None:
        if k != 1 or kill_state["victim"] is not None:
            return
        # rounds 0-1 established the sticky assignment; kill the replica
        # that owns shard 1, a beat AFTER round 2's shards go out so the
        # failover genuinely recovers an in-flight shard request
        detail = result.rounds[1]["shard_detail"]
        victim_name = next(d["replica"] for d in detail
                           if d["shard"] == 1)
        victim = next(r for r in reps if r.name == victim_name)
        kill_state["victim"] = victim_name
        kill_state["killed_at_round"] = k + 1

        def _kill():
            time.sleep(0.4)
            victim.process.send_signal(signal.SIGKILL)
            log(f"SIGKILLed {victim_name} (pid {victim.process.pid}) "
                f"with round {k + 1} in flight")
        threading.Thread(target=_kill, daemon=True).start()

    t0 = time.time()
    try:
        res = solve_portfolio(spec, backend="cpu", fleet=router,
                              request_id="pfsmoke", on_round=on_round)
        m = router.metrics()
    finally:
        router.close()
        for f in logs:
            f.close()
    wall = time.time() - t0

    # ---- gate 1: gap reached, 0 lost ---------------------------------
    assert kill_state["victim"] is not None, "kill never armed"
    if not res.converged or res.gap_rel > spec.gap_tol:
        raise AssertionError(
            f"dual loop did not reach the gap after the kill "
            f"(rounds {res.outer_rounds}, gap {res.gap_rel:.3e})")
    section = validate_portfolio_section(res.portfolio_section())
    assert section["shards"] == 2
    for r in res.rounds:
        got = sum(d["sites"] for d in r["shard_detail"])
        assert got == N_SITES, \
            f"round {r['round']}: {got}/{N_SITES} sites answered"

    # ---- gate 2: sticky before the kill, survivor-only after ---------
    pre = [r["shard_detail"] for r in res.rounds[:2]]
    homes = {d["shard"]: d["replica"] for d in pre[0]}
    assert set(homes.values()) == {"r0", "r1"}, \
        f"shards not spread over both replicas: {homes}"
    for rnd in pre[1:]:
        for d in rnd:
            assert d["replica"] == homes[d["shard"]], \
                f"sticky assignment broken before the kill: {pre}"
    victim = kill_state["victim"]
    survivor = next(n for n in ("r0", "r1") if n != victim)
    post = [r["shard_detail"] for r in res.rounds
            if r["round"] > kill_state["killed_at_round"]]
    assert post, "loop converged before any post-kill round"
    for rnd in post:
        for d in rnd:
            assert d["replica"] == survivor, \
                f"post-kill shard not on the survivor: {rnd}"

    # ---- gate 3: the failover machinery really fired -----------------
    r = m["routing"]
    assert r["failovers"] >= 1 or r["rerouted"] + r["harvested"] >= 1, \
        f"no failover recorded: {r}"
    assert m["replicas"][victim]["state"] == "dead", m["replicas"]

    # ---- gate 4: 100% certified --------------------------------------
    validate_portfolio_certification(res.certification)
    ps = res.certification["per_site"]
    if not ps["all_certified"] or res.certification["verdict"] not in (
            "certified", "certified_loose"):
        raise AssertionError(
            f"portfolio not fully certified: {res.certification}")

    print(json.dumps({
        "smoke": "portfolio_fleet", "ok": True,
        "sites": N_SITES, "shards": 2,
        "outer_rounds": res.outer_rounds,
        "gap_rel": res.gap_rel,
        "victim": victim, "survivor": survivor,
        "killed_at_round": kill_state["killed_at_round"],
        "failovers": r["failovers"], "rerouted": r["rerouted"],
        "harvested": r["harvested"],
        "memory_handoffs": r["memory_handoffs"],
        "verdict": res.certification["verdict"],
        "wall_s": round(wall, 1),
        "assignment": [{d["shard"]: d["replica"]
                        for d in rr["shard_detail"]}
                       for rr in res.rounds],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
