#!/usr/bin/env bash
# Reproduce every headline claim of this round from a clean checkout.
# Run from the repo root.  Expected results are noted per step (TPU
# numbers assume the single v5e chip this repo benches on; the remote
# tunnel shows +/-15% run-to-run noise).
set -euo pipefail

echo "=== 1. default test suite (~7 min; expect ~280 passed) ==="
python -m pytest tests/ -x -q

echo "=== 2. full suite incl. slow golden + CPU-vs-jax parity sweep"
echo "       (~35 min; expect ~355 passed) ==="
python -m pytest tests/ -q --runslow

echo "=== 3. north-star bench + product-scale legs (expect steady-state"
echo "       ~2.5-3s, vs_baseline ~20-25x, pallas:true, 24000/24000"
echo "       converged; sensitivity leg NPV parity <1e-2; long-horizon"
echo "       chip warm ~4-5s vs HiGHS ~6-20s at obj rel err ~6e-8) ==="
DERVET_TPU_NO_XLA_CACHE=1 python bench.py

REF="${DERVET_REFERENCE:-/root/reference}"

if [ -d "$REF" ]; then
    echo "=== 4. real-case NPV gate (expect rel err ~1.7e-3, exit 0) ==="
    BENCH_REAL_CASE=1 BENCH_SCENARIOS=50 python bench.py
else
    echo "=== 4. SKIPPED: reference checkout not found at $REF ==="
fi

echo "=== 5. driver hooks: single-chip compile + multi-chip dryrun ==="
python __graft_entry__.py

if [ -d "$REF" ]; then
    echo "=== 6. end-to-end CLI on a reference input ==="
    out=$(mktemp -d)
    python run_dervet_tpu.py \
        "$REF/test/test_storagevet_features/model_params/009-bat_energy_sensitivity.csv" \
        --base-path "$REF" --out "$out"
    ls "$out" | head
    test -f "$out/sensitivity_summary.csv" && echo "sensitivity_summary.csv OK"
else
    echo "=== 6. SKIPPED: reference checkout not found at $REF ==="
fi

echo "ALL REPRODUCTION STEPS PASSED"
