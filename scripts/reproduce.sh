#!/usr/bin/env bash
# Reproduce every headline claim of this round from a clean checkout.
# Run from the repo root.  Expected results are noted per step (TPU
# numbers assume the single v5e chip this repo benches on; the remote
# tunnel shows +/-15% run-to-run noise).
set -euo pipefail

echo "=== 1. default test suite (~7 min; expect ~283 passed, incl. the"
echo "       5-input cpu-vs-jax parity slice) ==="
python -m pytest tests/ -x -q

echo "=== 2. full suite incl. slow golden + CPU-vs-jax parity sweep +"
echo "       independent-formulation cross-check (~50 min) ==="
python -m pytest tests/ -q --runslow

echo "=== 2b. independent-formulation cross-check alone (8 families,"
echo "        expect every rel err <= ~1e-10) ==="
python scripts/crosscheck_formulation.py

echo "=== 3. north-star bench + product-scale legs (expect steady-state"
echo "       ~2.0-2.5s, vs_baseline ~25-30x, pallas:true, 24000/24000"
echo "       converged, a utilization block per leg; sensitivity leg"
echo "       ~2.2-2.9x warm over serial CPU with a phase split;"
echo "       long-horizon end-to-end ~4.4-7s vs HiGHS ~6-8s at obj rel"
echo "       err ~4e-7) ==="
DERVET_TPU_NO_XLA_CACHE=1 python bench.py

REF="${DERVET_REFERENCE:-/root/reference}"

if [ -d "$REF" ]; then
    echo "=== 4. real-case NPV gate (expect rel err ~1.7e-3, exit 0) ==="
    BENCH_REAL_CASE=1 BENCH_SCENARIOS=50 python bench.py
else
    echo "=== 4. SKIPPED: reference checkout not found at $REF ==="
fi

echo "=== 4b. serving smoke: concurrent requests through the scenario"
echo "        service (expect ok:true, 100% certified, coalesced_groups"
echo "        >= 1, warm_repeat_compile_events 0, exit 0) ==="
JAX_PLATFORMS=cpu python scripts/serve_smoke.py

echo "=== 5. driver hooks: single-chip compile + multi-chip dryrun ==="
python __graft_entry__.py

if [ -d "$REF" ]; then
    echo "=== 6. end-to-end CLI on a reference input ==="
    out=$(mktemp -d)
    python run_dervet_tpu.py \
        "$REF/test/test_storagevet_features/model_params/009-bat_energy_sensitivity.csv" \
        --base-path "$REF" --out "$out"
    ls "$out" | head
    test -f "$out/sensitivity_summary.csv" && echo "sensitivity_summary.csv OK"
else
    echo "=== 6. SKIPPED: reference checkout not found at $REF ==="
fi

echo "ALL REPRODUCTION STEPS PASSED"
