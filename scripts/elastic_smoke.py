"""CI smoke: elastic mesh serving on 8 forced host devices, no chip.

Boots the :class:`~dervet_tpu.service.server.ScenarioService` on an
8-virtual-device CPU XLA mesh and drills the elastic scheduler
(parallel/elastic.py) end to end:

* N concurrent requests with DIFFERENT window lengths fan one round out
  to > 8 structure groups — every device must receive at least one
  group (mesh-wide placement actually happened);
* results are BYTE-IDENTICAL to a single-device elastic schedule
  (``DERVET_TPU_ELASTIC_DEVICES=1``) on a fresh service — objectives
  and the full solution-array surface: placement, mesh size, and
  stealing never change what a window solves to;
* 100% of windows carry an accepted float64 certificate;
* a warm repeat round compiles NOTHING (the per-device shard caches +
  warm-start memory keep the zero-compile hot-serving contract);
* under the ``straggler`` fault (device 0 slowed), a fresh round records
  >= 1 work steal and still completes correct.

Env knobs: SMOKE_ELASTIC_LENGTHS (default 10 distinct window lengths),
SMOKE_ELASTIC_CASES (cases per request, default 2).
"""
from __future__ import annotations

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _workload(n_lengths: int, cases_per: int):
    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    out = {}
    for i in range(n_lengths):
        n = 72 + 24 * i
        cases = synthetic_sensitivity_cases(cases_per, months=1, n=n)
        out[f"el{i}"] = {j: c for j, c in enumerate(cases)}
    return out


def _serve(workload, rid_prefix=""):
    """Submit the whole workload, then drive ONE deterministic
    ``run_once`` round (no batcher thread: a background round could
    split the wave and leave ``last_round_ledger`` covering only the
    tail — the device-coverage assertions need the full round)."""
    from dervet_tpu.service import ScenarioService
    svc = ScenarioService(backend="jax", max_wait_s=0.0,
                          max_batch_requests=64)
    try:
        futs = {rid: svc.submit(cases, request_id=f"{rid_prefix}{rid}")
                for rid, cases in workload.items()}
        svc.run_once()
        results = {rid: f.result(timeout=900) for rid, f in futs.items()}
        return svc, results
    except BaseException:
        svc.close()
        raise


def main() -> int:
    import numpy as np

    from dervet_tpu.benchlib import validate_solve_ledger

    n_lengths = int(os.environ.get("SMOKE_ELASTIC_LENGTHS", "10"))
    cases_per = int(os.environ.get("SMOKE_ELASTIC_CASES", "2"))
    n_dev = len(jax.devices())
    assert n_dev == 8, f"smoke expects 8 forced host devices, got {n_dev}"

    # -- elastic pass ---------------------------------------------------
    os.environ.pop("DERVET_TPU_ELASTIC", None)
    svc, results = _serve(_workload(n_lengths, cases_per))
    try:
        ledger = svc.last_round_ledger
        validate_solve_ledger(ledger)
        el = ledger.get("elastic")
        if not el:
            raise AssertionError("no elastic section in the round ledger")
        if el["devices_with_groups"] != n_dev:
            raise AssertionError(
                f"only {el['devices_with_groups']}/{n_dev} devices "
                f"received groups: {el['devices']}")
        total_windows = 0
        for rid, res in results.items():
            cert = res.run_health["certification"]
            n_windows = sum(len(inst.scenario.windows)
                            for inst in res.instances.values())
            total_windows += n_windows
            if not cert["enabled"] or \
                    cert["windows_certified"] != n_windows:
                raise AssertionError(
                    f"{rid}: {cert['windows_certified']}/{n_windows} "
                    "windows certified (acceptance: 100%)")

        # warm repeat: identical workload, zero compiles anywhere
        futs = {rid: svc.submit(cases, request_id=f"warm.{rid}")
                for rid, cases in _workload(n_lengths, cases_per).items()}
        svc.run_once()
        for f in futs.values():
            f.result(timeout=900)
        warm_compiles = svc.last_round_ledger["totals"]["compile_events"]
        if warm_compiles:
            raise AssertionError(
                f"warm elastic round compiled {warm_compiles} program(s) "
                "— the zero-compile hot-serving contract is broken")
        metrics = svc.metrics()
    finally:
        svc.close()

    # -- single-device schedule: byte identity ---------------------------
    os.environ["DERVET_TPU_ELASTIC_DEVICES"] = "1"
    try:
        svc_s, results_s = _serve(_workload(n_lengths, cases_per))
        svc_s.close()
    finally:
        os.environ.pop("DERVET_TPU_ELASTIC_DEVICES", None)
    for rid, res in results.items():
        ref = results_s[rid]
        for key in res.instances:
            se = res.instances[key].scenario
            ss = ref.instances[key].scenario
            if se.objective_values != ss.objective_values:
                raise AssertionError(f"objective mismatch {rid}/{key}")
            for name in se._solution:
                if not np.array_equal(se._solution[name],
                                      ss._solution[name]):
                    raise AssertionError(
                        f"solution mismatch {rid}/{key}/{name}")

    # -- straggler drill: device 0 slowed, >= 1 steal --------------------
    os.environ["DERVET_TPU_FAULT_STRAGGLER"] = "1"
    os.environ["DERVET_TPU_FAULT_STRAGGLER_DEVICE"] = "0"
    # 1.5 s: the slowdown must dwarf one group's solve for the steal
    # window to open deterministically (the r14 reflected default cut
    # solve times ~30%; 0.6 s started racing the victim's queue drain)
    os.environ["DERVET_TPU_FAULT_STRAGGLER_S"] = "1.5"
    try:
        svc_f, results_f = _serve(_workload(n_lengths, cases_per))
        try:
            led_f = svc_f.last_round_ledger
            el_f = led_f.get("elastic") or {}
            if not el_f.get("n_steals"):
                raise AssertionError(
                    f"no work steal under the straggler fault: {el_f}")
            for rid, res in results_f.items():
                cert = res.run_health["certification"]
                n_windows = sum(len(inst.scenario.windows)
                                for inst in res.instances.values())
                if cert["windows_certified"] != n_windows:
                    raise AssertionError(
                        f"straggler drill: {rid} lost certification")
        finally:
            svc_f.close()
    finally:
        for k in ("DERVET_TPU_FAULT_STRAGGLER",
                  "DERVET_TPU_FAULT_STRAGGLER_DEVICE",
                  "DERVET_TPU_FAULT_STRAGGLER_S"):
            os.environ.pop(k, None)

    print(json.dumps({
        "smoke": "elastic", "ok": True,
        "devices": n_dev,
        "requests": n_lengths,
        "windows": total_windows,
        "devices_with_groups": el["devices_with_groups"],
        "placement_steals": el["n_steals"],
        "straggler_steals": el_f["n_steals"],
        "warm_repeat_compile_events": warm_compiles,
        "occupancy": {d: rec["occupancy"]
                      for d, rec in el["devices"].items()},
        "elastic_metrics": metrics["elastic"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
