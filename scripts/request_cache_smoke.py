"""CI smoke: the router's request-level memoization plane, end to end.

Boots a 2-replica fleet (real ``dervet-tpu serve`` subprocesses over
file spools, CPU backend) and drills the four contracts of the
admission-time result cache (``dervet_tpu.service.reqcache``):

* **repeat wave** — a second wave of identical-content requests under
  fresh ids is answered straight from the router's content-addressed
  result cache: ZERO replica dispatches (the new ids never appear in
  any replica's service journal), byte-identical CSV artifacts, and a
  hit-path latency far below the cold solve;
* **fleet-wide dedup** — N identical CO-PENDING requests coalesce at
  admission into one replica solve; every rid resolves, followers are
  flagged ``coalesced`` and journaled individually (exactly-once
  delivery surface intact);
* **delta solves** — ``submit_delta(base, edited)`` with a one-window
  time-series edit re-dispatches ONLY the changed window.  Two drills:
  on the exact cpu fleet the journal diff note says
  ``windows_changed == 1`` and the merged answer is byte-identical to
  a full cold re-solve of the edited case on a fresh fleet; on a jax
  replica (the backend that carries the warm-start memory plane) the
  delta run's solve ledger shows every unchanged window
  exact-substituted from the base solve's stored solutions — zero
  device re-solves outside the edit;
* **kill switch** — ``DERVET_TPU_REQUEST_CACHE=0`` restores the plain
  path bit for bit: repeats reach the replicas again, results stay
  byte-identical, and no cache files or directories are created.

Env knobs: SMOKE_RC_REQUESTS (default 3), SMOKE_RC_DUPLICATES
(default 4), SMOKE_RC_DEADLINE_S (default 600).
"""
from __future__ import annotations

import copy
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# a small per-request solve floor (fault-injected outside the solver)
# so the co-pending dedup wave reliably overlaps; correctness untouched
os.environ.setdefault("DERVET_TPU_FAULT_SLOW", "1")
os.environ.setdefault("DERVET_TPU_FAULT_SLOW_S", "1.0")

N_REQ = int(os.environ.get("SMOKE_RC_REQUESTS", "3"))
N_DUP = int(os.environ.get("SMOKE_RC_DUPLICATES", "4"))
DEADLINE_S = float(os.environ.get("SMOKE_RC_DEADLINE_S", "600"))


def log(msg: str) -> None:
    print(f"request-cache-smoke: {msg}", file=sys.stderr, flush=True)


def workload():
    """N requests, one case each: distinct window lengths (distinct LP
    structures) and distinct battery ratings (distinct content)."""
    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    out = {}
    for i in range(N_REQ):
        case = synthetic_sensitivity_cases(1, n=48 + 24 * i, months=1)[0]
        for tag, _, keys in case.ders:
            if tag == "Battery":
                keys["ene_max_rated"] = 8000.0 + 10.0 * i
        out[f"req{i:02d}"] = {0: case}
    return out


def delta_base_case(days=31):
    """A 24h-window case (``days`` windows) for the delta drills — a
    structure distinct from every workload() request so affinity
    routes the delta to the replica holding the base solve."""
    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    case = synthetic_sensitivity_cases(1, n=24, months=1)[0]
    if days < 31:
        ts = case.datasets.time_series
        case.datasets.time_series = ts.loc[ts.index.day <= days]
    return {0: case}


def edit_one_window(cases, bump=0.05):
    """Deep-copy ``cases`` and poke one DA price value inside the
    SECOND 24h window only — an edit the delta plane localizes to
    window 1 and that genuinely changes that window's LP (so the
    byte-identity gate compares real re-solved bytes, not a no-op)."""
    edited = copy.deepcopy(cases)
    ts = edited[0].datasets.time_series
    col = ts.columns.get_loc("DA Price ($/kWh)")
    ts.iloc[30, col] += bump
    return edited


def spawn_fleet(root: Path, n: int, tag: str):
    from dervet_tpu.service import spawn_replica
    reps = []
    for i in range(n):
        name = f"{tag}{i}"
        logf = open(root / f"{name}.log", "w")
        reps.append(spawn_replica(root / name, name=name, backend="cpu",
                                  stdout=logf, stderr=logf))
    return reps


def route_wave(router, reqs, rid_prefix=""):
    return {rid_prefix + rid: router.submit(
                cases, request_id=rid_prefix + rid, deadline_s=DEADLINE_S)
            for rid, cases in reqs.items()}


def collect(futs, timeout=900):
    return {rid: fut.result(timeout=timeout) for rid, fut in futs.items()}


def csv_surface(results_dir: Path):
    return {p.name: p.read_bytes()
            for p in sorted(results_dir.glob("*.csv"))}


def replica_rids(reps):
    """Every rid any replica ever admitted (from the service journals)."""
    from dervet_tpu.service import ServiceJournal
    seen = set()
    for rep in reps:
        path = rep.spool / "service_journal.jsonl"
        if path.exists():
            seen.update(ServiceJournal.replay_path(path))
    return seen


def assert_certified(rid, res):
    rh = res.load_run_health()
    assert rh is not None, f"{rid}: no run-health slice"
    cert = rh["certification"]
    assert cert["enabled"], f"{rid}: certification disabled"
    assert cert["windows"]["rejected_final"] == 0, \
        f"{rid}: final certificate rejections"


def load_ledger(res):
    named = res.results_dir / f"solve_ledger.{res.rid}.json"
    path = named if named.exists() else res.results_dir / "solve_ledger.json"
    return json.loads(path.read_text())


def main() -> int:
    import tempfile

    from dervet_tpu.service import FleetRouter

    workdir = Path(tempfile.mkdtemp(prefix="reqcache-smoke-"))
    report = {"requests": N_REQ, "duplicates": N_DUP}
    root = workdir / "fleet"
    root.mkdir()
    reps = spawn_fleet(root, 2, "r")
    router = FleetRouter(reps, fleet_dir=root / "router",
                         heartbeat_timeout_s=5.0, tick_s=0.05).start()

    # ---- wave A: cold solves ----------------------------------------
    log(f"wave A: {N_REQ} cold solves …")
    t0 = time.time()
    results_a = collect(route_wave(router, workload()))
    report["cold_wall_s"] = round(time.time() - t0, 1)
    cold_lat = sorted(r.latency_s for r in results_a.values())
    a_csvs = {}
    for rid, res in results_a.items():
        assert not res.cached, f"{rid}: cold solve flagged cached"
        assert_certified(rid, res)
        a_csvs[rid] = csv_surface(res.results_dir)
        assert a_csvs[rid], f"{rid}: empty CSV surface"
    rids_after_a = replica_rids(reps)
    log(f"wave A done in {report['cold_wall_s']}s")

    # ---- wave B: identical content, fresh ids → pure cache hits -----
    log("wave B: repeat wave (cache hits) …")
    results_b = collect(route_wave(router, workload(), rid_prefix="w2."))
    hit_lat = sorted(r.latency_s for r in results_b.values())
    for rid, res in results_b.items():
        assert res.cached, f"{rid}: repeat request missed the cache"
        assert res.replica == "request_cache", (rid, res.replica)
        assert_certified(rid, res)
        got = csv_surface(res.results_dir)
        ref = a_csvs[rid[len("w2."):]]
        assert sorted(got) == sorted(ref), \
            f"{rid}: cached CSV file set differs"
        for name in ref:
            assert got[name] == ref[name], \
                f"{rid}/{name}: cached bytes differ from cold solve"
    # ZERO replica dispatches: no wave-B rid ever reached a replica
    leaked = replica_rids(reps) - rids_after_a
    assert not (leaked & set(results_b)), \
        f"cache-hit rids reached a replica: {sorted(leaked)}"
    m = router.metrics()["routing"]
    assert m["request_cache_hits"] == N_REQ, m
    assert m["request_cache_stores"] >= N_REQ, m
    cold_p50 = cold_lat[len(cold_lat) // 2]
    hit_p50 = hit_lat[len(hit_lat) // 2]
    assert hit_p50 < 0.2 * cold_p50, \
        f"hit p50 {hit_p50:.3f}s not << cold p50 {cold_p50:.3f}s"
    report.update({
        "cold_p50_s": round(cold_p50, 3), "hit_p50_s": round(hit_p50, 4),
        "hit_speedup": round(cold_p50 / max(hit_p50, 1e-9), 1),
    })
    log(f"wave B: {N_REQ}/{N_REQ} hits, p50 {hit_p50 * 1e3:.0f}ms "
        f"vs cold {cold_p50:.1f}s")

    # ---- dedup: N identical co-pending requests → one solve ---------
    log(f"dedup: {N_DUP} identical co-pending …")
    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    dup_case = {0: synthetic_sensitivity_cases(1, n=60, months=1)[0]}
    dup_futs = {f"dup{i}": router.submit(
                    copy.deepcopy(dup_case), request_id=f"dup{i}",
                    deadline_s=DEADLINE_S)
                for i in range(N_DUP)}
    dup_results = collect(dup_futs)
    dispatched = replica_rids(reps) & set(dup_futs)
    assert len(dispatched) == 1, \
        f"dedup leaked {len(dispatched)} dispatches: {sorted(dispatched)}"
    coalesced = [rid for rid, r in dup_results.items() if r.coalesced]
    assert len(coalesced) == N_DUP - 1, (coalesced, N_DUP)
    m = router.metrics()["routing"]
    assert m["duplicates_coalesced"] == N_DUP - 1, m
    base_surface = None
    for rid, res in dup_results.items():
        assert_certified(rid, res)
        got = csv_surface(res.results_dir)
        if base_surface is None:
            base_surface = got
        assert got == base_surface, f"{rid}: coalesced bytes differ"
    # exactly-once delivery surface: every rid journaled individually
    events = [json.loads(ln) for ln in
              (root / "router" /
               "fleet_journal.jsonl").read_text().splitlines()]
    done = {e["rid"] for e in events if e["event"] == "completed"}
    assert set(dup_futs) <= done, sorted(set(dup_futs) - done)
    report["duplicates_coalesced"] = len(coalesced)
    log(f"dedup: 1 solve for {N_DUP} requests "
        f"({len(coalesced)} coalesced)")

    # ---- delta: one-window edit, cpu byte-identity ------------------
    log("delta: base solve, then a one-window edit …")
    base = delta_base_case()
    res_base = router.submit(copy.deepcopy(base), request_id="delta.base",
                             deadline_s=DEADLINE_S).result(timeout=900)
    assert_certified("delta.base", res_base)
    edited = edit_one_window(base)
    res_delta = router.submit_delta(
        base, copy.deepcopy(edited), request_id="delta.edit",
        deadline_s=DEADLINE_S).result(timeout=900)
    assert_certified("delta.edit", res_delta)
    events = [json.loads(ln) for ln in
              (root / "router" /
               "fleet_journal.jsonl").read_text().splitlines()]
    note = [e for e in events if e["event"] == "delta"
            and e["rid"] == "delta.edit"]
    assert note and note[0]["windows_changed"] == 1, note
    total = note[0]["windows_total"]
    m = router.metrics()["routing"]
    assert m["delta_requests"] == 1, m
    report.update({"delta_windows_total": total,
                   "delta_windows_changed": 1})
    log(f"delta: diff localized to 1/{total} windows")

    # merged answer byte-identical to a full cold re-solve of the
    # edited case on a FRESH fleet (cpu backend contract)
    log("delta: cold re-solve reference …")
    cold_root = workdir / "coldref"
    cold_root.mkdir()
    cold_reps = spawn_fleet(cold_root, 1, "c")
    cold_router = FleetRouter(cold_reps, fleet_dir=cold_root / "router",
                              heartbeat_timeout_s=5.0).start()
    try:
        res_cold = cold_router.submit(
            copy.deepcopy(edited), request_id="delta.cold",
            deadline_s=DEADLINE_S).result(timeout=900)
        got = csv_surface(res_delta.results_dir)
        ref = csv_surface(res_cold.results_dir)
        assert sorted(got) == sorted(ref) and got, \
            "delta CSV file set differs from cold re-solve"
        for name in ref:
            assert got[name] == ref[name], \
                f"delta/{name}: bytes differ from full cold re-solve"
    finally:
        cold_router.close()
    report["delta_byte_identical"] = True
    log("delta: byte-identical to the cold re-solve")
    router.close()

    # ---- delta warm plane: only the changed window re-solves --------
    # the warm-start memory (exact substitution) lives on the batched
    # jax path, so this drill runs one jax replica (pinned to CPU XLA):
    # the delta's ledger must show every unchanged window shipped from
    # the base solve's stored solutions
    log("delta warm plane: jax replica …")
    from dervet_tpu.service import spawn_replica
    jax_root = workdir / "jaxdelta"
    jax_root.mkdir()
    jlog = open(jax_root / "j0.log", "w")
    jrep = spawn_replica(jax_root / "j0", name="j0", backend="jax",
                         stdout=jlog, stderr=jlog)
    jrouter = FleetRouter([jrep], fleet_dir=jax_root / "router",
                          heartbeat_timeout_s=5.0).start()
    try:
        jbase = delta_base_case(days=10)
        jres = jrouter.submit(copy.deepcopy(jbase),
                              request_id="jd.base",
                              deadline_s=DEADLINE_S).result(timeout=900)
        assert_certified("jd.base", jres)
        jedited = edit_one_window(jbase)
        jres_d = jrouter.submit_delta(
            jbase, jedited, request_id="jd.edit",
            deadline_s=DEADLINE_S).result(timeout=900)
        assert_certified("jd.edit", jres_d)
        jledger = load_ledger(jres_d)
        jtotal = int(jledger["totals"]["windows"])
        # the per-request ledger slice carries warm accounting per
        # group (initial rungs), not the run-level warm_start rollup
        substituted = sum(
            int((g.get("warm") or {}).get("substituted") or 0)
            for g in jledger.get("groups", [])
            if g.get("rung") in (None, "initial"))
        assert substituted >= jtotal - 2, \
            f"delta re-solved too much: {substituted}/{jtotal} " \
            "windows substituted for a 1-window edit"
    finally:
        jrouter.close()
    report.update({"delta_jax_windows": jtotal,
                   "delta_jax_substituted": substituted})
    log(f"delta warm plane: {substituted}/{jtotal} windows "
        "exact-substituted (1-window edit)")

    # ---- kill switch: plain path, bit for bit, zero cache files -----
    log("kill switch: DERVET_TPU_REQUEST_CACHE=0 …")
    os.environ["DERVET_TPU_REQUEST_CACHE"] = "0"
    try:
        off_reps = spawn_fleet(root, 2, "k")
        off_router = FleetRouter(off_reps, fleet_dir=root / "router_off",
                                 heartbeat_timeout_s=5.0,
                                 tick_s=0.05).start()
        try:
            off_a = collect(route_wave(off_router, workload(),
                                       rid_prefix="off."))
            off_b = collect(route_wave(off_router, workload(),
                                       rid_prefix="off2."))
            seen = replica_rids(off_reps)
            for rid, res in {**off_a, **off_b}.items():
                assert not res.cached and not res.coalesced, rid
                assert rid in seen, \
                    f"{rid}: never reached a replica with the cache off"
                assert_certified(rid, res)
                ref = a_csvs[rid.split(".", 1)[1]]
                got = csv_surface(res.results_dir)
                for name in ref:
                    assert got[name] == ref[name], \
                        f"{rid}/{name}: kill-switch bytes differ"
            c = off_router.metrics()["routing"]
            assert c["request_cache_hits"] == 0, c
            assert c["request_cache_stores"] == 0, c
            assert c["duplicates_coalesced"] == 0, c
            cache_dirs = [p for p in (root / "router_off").rglob("*")
                          if "result_cache" in p.name]
            assert not cache_dirs, \
                f"kill switch left cache files: {cache_dirs}"
        finally:
            off_router.close()
    finally:
        del os.environ["DERVET_TPU_REQUEST_CACHE"]
    report["kill_switch_byte_identical"] = True
    log("kill switch: plain path bit for bit, zero cache files")

    report["ok"] = True
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
