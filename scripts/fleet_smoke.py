"""CI smoke: the multi-replica fleet under a real SIGKILL, exactly-once.

Boots a 3-replica fleet (real ``dervet-tpu serve`` subprocesses over
file spools, CPU backend), routes a mixed-structure workload through
:class:`~dervet_tpu.service.router.FleetRouter`, and SIGKILLs one
replica mid-round.  The serving contract under fire:

* **0 lost** — every request's future resolves (the dead replica's
  in-flight requests are recovered from its journal + spool and
  re-routed or harvested);
* **0 duplicated** — each request is DELIVERED exactly once (late
  answers from the killed replica are suppressed, never double-served);
* **100% certified** — every delivered run-health slice carries a full
  complement of accepted float64 certificates, recovered requests
  included;
* **byte-identical** — the full result-CSV surface matches the same
  workload served by a single-replica fleet (failover changes WHERE a
  request solves, never what it solves to);
* **failover < deadline** — every request answered inside its deadline
  despite the kill, and the router's failover-latency metric is bounded;
* **visible** — the dead replica's breaker is open and the failover /
  reroute / harvest counters are nonzero in ``FleetRouter.metrics()``.

A second wave of identical-content requests then exercises the warm
tier: structure-fingerprint affinity hits and (replica-local) exact
warm-start repeats, still byte-identical.

Env knobs: SMOKE_FLEET_REQUESTS (default 6), SMOKE_FLEET_DEADLINE_S
(default 300), SMOKE_FLEET_SLOW_S (default 0.75 — per-solve injected
delay so the SIGKILL reliably lands mid-round).
"""
from __future__ import annotations

import json
import os
import signal
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# this smoke drills the REPLICA tier (failover, affinity, warm-start
# repeats): wave 2's identical-content repeats must actually reach the
# replicas, so the router's request-level memoization plane is pinned
# off here — it has its own smoke (request_cache_smoke.py)
os.environ["DERVET_TPU_REQUEST_CACHE"] = "0"

N_REQ = int(os.environ.get("SMOKE_FLEET_REQUESTS", "6"))
DEADLINE_S = float(os.environ.get("SMOKE_FLEET_DEADLINE_S", "300"))
SLOW_S = os.environ.get("SMOKE_FLEET_SLOW_S", "0.75")


def log(msg: str) -> None:
    print(f"fleet-smoke: {msg}", file=sys.stderr, flush=True)


def workload():
    """N requests, one case each: DISTINCT window lengths (distinct LP
    structures — cross-request warm seeding cannot blur the byte-
    identity gate) and distinct battery ratings (distinct content)."""
    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    out = {}
    for i in range(N_REQ):
        case = synthetic_sensitivity_cases(1, n=72 + 24 * i, months=1)[0]
        for tag, _, keys in case.ders:
            if tag == "Battery":
                keys["ene_max_rated"] = 8000.0 + 10.0 * i
        out[f"req{i:02d}"] = {0: case}
    return out


def spawn_fleet(root: Path, n: int, tag: str):
    from dervet_tpu.service import spawn_replica
    # every replica (reference included) carries the same slow-solve
    # fault so the two passes stay byte-comparable and the kill lands
    # mid-round; the delay is outside the solver — correctness untouched
    env = {"DERVET_TPU_FAULT_SLOW": "all",
           "DERVET_TPU_FAULT_SLOW_S": SLOW_S}
    reps = []
    for i in range(n):
        name = f"{tag}{i}"
        logf = open(root / f"{name}.log", "w")
        reps.append(spawn_replica(root / name, name=name, backend="cpu",
                                  stdout=logf, stderr=logf, env=env))
    return reps


def route_wave(router, reqs, rid_prefix=""):
    futs = {}
    for rid, cases in reqs.items():
        futs[rid_prefix + rid] = router.submit(
            cases, request_id=rid_prefix + rid, deadline_s=DEADLINE_S)
    return futs


def collect(futs, timeout=600):
    out = {}
    for rid, fut in futs.items():
        out[rid] = fut.result(timeout=timeout)
    return out


def csv_surface(results_dir: Path):
    return {p.name: p.read_bytes()
            for p in sorted(results_dir.glob("*.csv"))}


def assert_certified(rid, res):
    rh = res.load_run_health()
    assert rh is not None, f"{rid}: no run-health slice"
    cert = rh["certification"]
    assert cert["enabled"], f"{rid}: certification disabled"
    assert cert["windows"]["rejected_final"] == 0, \
        f"{rid}: final certificate rejections"
    # 100% coverage: every window the ledger slice dispatched carries an
    # accepted certificate
    ledger = json.loads(
        (res.results_dir / f"solve_ledger.{res.rid}.json").read_text())
    n_windows = ledger["totals"]["windows"]
    assert cert["windows_certified"] == n_windows > 0, \
        f"{rid}: {cert['windows_certified']}/{n_windows} windows " \
        "certified (acceptance: 100%)"


def main() -> int:
    import tempfile

    from dervet_tpu.service import FleetRouter, ServiceJournal

    workdir = Path(tempfile.mkdtemp(prefix="fleet-smoke-"))
    report = {"requests": N_REQ}

    # ---- reference pass: the same workload on a single replica -------
    log("reference pass: 1 replica …")
    ref_root = workdir / "ref"
    ref_root.mkdir()
    ref_reps = spawn_fleet(ref_root, 1, "ref")
    ref_router = FleetRouter(ref_reps, fleet_dir=ref_root / "fleet",
                             heartbeat_timeout_s=5.0).start()
    t0 = time.time()
    ref_results = collect(route_wave(ref_router, workload()))
    report["reference_wall_s"] = round(time.time() - t0, 1)
    ref_csvs = {rid: csv_surface(r.results_dir)
                for rid, r in ref_results.items()}
    ref_router.close()
    log(f"reference: {len(ref_results)} requests in "
        f"{report['reference_wall_s']}s")

    # ---- fleet pass: 3 replicas, SIGKILL one mid-round ---------------
    log("fleet pass: 3 replicas …")
    fleet_root = workdir / "fleet"
    fleet_root.mkdir()
    reps = spawn_fleet(fleet_root, 3, "r")
    router = FleetRouter(reps, fleet_dir=fleet_root / "router",
                         heartbeat_timeout_s=3.0, tick_s=0.05).start()
    futs = route_wave(router, workload())

    # pick the victim: a replica with >= 1 COMPLETED request (so its
    # warm-start export exists for the handoff) and >= 1 admitted
    # request still unfinished (so the kill genuinely lands mid-round)
    victim = None
    kill_deadline = time.time() + 240
    while victim is None and time.time() < kill_deadline:
        for rep in reps:
            states = ServiceJournal.replay_path(
                rep.spool / "service_journal.jsonl")
            done = sum(1 for e in states.values()
                       if e["state"] == "completed")
            inflight = sum(1 for e in states.values()
                           if e["state"] == "admitted")
            if done >= 1 and inflight >= 1 and \
                    (rep.spool / "memory_export.pkl").exists():
                victim = rep
                break
        time.sleep(0.05)
    assert victim is not None, \
        "no replica reached completed>=1 + inflight>=1 before the " \
        "workload drained — kill window missed"
    t_kill = time.time()
    victim.process.send_signal(signal.SIGKILL)
    log(f"SIGKILLed replica {victim.name} (pid {victim.process.pid}) "
        "mid-round")

    results = collect(futs)
    t_all = time.time()

    # ---- the contract -------------------------------------------------
    assert set(results) == set(ref_results), "lost requests"
    recovered = [rid for rid, r in results.items() if r.recovered]
    assert recovered, "kill drill produced no recovered request — the " \
        "victim had nothing in flight (drill is vacuous)"
    byte_identical = True
    for rid, res in results.items():
        assert_certified(rid, res)
        got = csv_surface(res.results_dir)
        ref = ref_csvs[rid]
        assert sorted(got) == sorted(ref) and got, \
            f"{rid}: CSV file set differs from single-replica run"
        for name in ref:
            if got[name] != ref[name]:
                byte_identical = False
                log(f"BYTE MISMATCH {rid}/{name} "
                    f"(served by {res.replica}, "
                    f"recovered={res.recovered})")
    assert byte_identical, "fleet results not byte-identical to the " \
        "single-replica run"

    m = router.metrics()
    r = m["routing"]
    assert r["failovers"] >= 1, r
    assert r["rerouted"] + r["harvested"] >= 1, r
    assert m["replicas"][victim.name]["state"] == "dead"
    assert m["replicas"][victim.name]["breaker"]["state"] == "open", \
        m["replicas"][victim.name]["breaker"]
    # exactly-once at the delivery layer: completed counts every rid
    # once, and nothing was double-delivered (a second set_result would
    # have raised InvalidStateError inside the router)
    assert r["completed"] == N_REQ, r
    assert r["failed"] == 0, r
    failover_wall = t_all - t_kill
    assert failover_wall < DEADLINE_S, \
        f"failover took {failover_wall:.0f}s (deadline {DEADLINE_S:g}s)"
    report.update({
        "victim": victim.name,
        "recovered_requests": recovered,
        "harvested": r["harvested"], "rerouted": r["rerouted"],
        "duplicates_suppressed": r["duplicates_suppressed"],
        "memory_handoffs": r["memory_handoffs"],
        "failover_wall_s": round(failover_wall, 1),
        "failover_latency_s": m["failover_latency_s"],
        "byte_identical": byte_identical,
    })
    log(f"kill drill OK: {len(recovered)} recovered "
        f"({r['harvested']} harvested, {r['rerouted']} rerouted, "
        f"{r['memory_handoffs']} memory handoffs), failover wall "
        f"{failover_wall:.1f}s, byte-identical")

    # ---- wave 2: affinity + warm repeats on the surviving fleet ------
    log("wave 2: identical content, new ids …")
    futs2 = route_wave(router, workload(), rid_prefix="w2.")
    results2 = collect(futs2)
    for rid, res in results2.items():
        assert_certified(rid, res)
        got = csv_surface(res.results_dir)
        ref = ref_csvs[rid[len("w2."):]]
        for name in ref:
            assert got[name] == ref[name], \
                f"wave2 {rid}/{name}: bytes differ from reference"
    m2 = router.metrics()
    assert m2["routing"]["affinity_hits"] >= 1, \
        "no affinity hit on the repeat wave"
    report["affinity_hit_rate"] = m2["routing"]["affinity_hit_rate"]
    router.close()

    report["ok"] = True
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
