"""CI smoke: the BOOST design service on the cpu XLA backend, no chip.

Boots a :class:`~dervet_tpu.service.server.ScenarioService`
(backend="jax" on a CPU XLA device — the same no-hardware analogue the
serve smoke uses), submits one 512-candidate design request (top-8
certified frontier), and asserts the design contract:

* the frontier is non-empty and 100% of finalists carry an accepted
  PR-4 float64 certificate;
* the certified winner's SCREENING rank is within the top-k (the
  ordinal screen actually ordered the population);
* the screening phase rode the batch axis: its device-dispatch count is
  at least 10x smaller than solving the candidates solo would cost
  (>= 1 dispatch per candidate);
* a WARM repeat of the same request compiles ZERO XLA programs in both
  the screening tiers and the certified round (the persistent per-tier
  screening caches + bucket-grid padding).

Env knobs: SMOKE_POPULATION (default 512), SMOKE_TOPK (default 8),
SMOKE_HOURS (default 72 — the synthetic case's horizon).
"""
from __future__ import annotations

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax

jax.config.update("jax_platforms", "cpu")


def make_case(hours: int):
    from dervet_tpu.benchlib import synthetic_case
    c = synthetic_case()
    c.scenario["allow_partial_year"] = True
    c.datasets.time_series = c.datasets.time_series.iloc[:hours]
    return c


def main() -> int:
    from dervet_tpu.design import DERBounds, DesignSpec
    from dervet_tpu.service import ScenarioService

    population = int(os.environ.get("SMOKE_POPULATION", "512"))
    top_k = int(os.environ.get("SMOKE_TOPK", "8"))
    hours = int(os.environ.get("SMOKE_HOURS", "72"))

    spec = DesignSpec(
        bounds={("Battery", "1"): DERBounds(kw=(250.0, 2500.0),
                                            kwh=(500.0, 9000.0))},
        population=population, top_k=top_k, refine_rounds=1)

    svc = ScenarioService(backend="jax", max_wait_s=0.05)
    svc.start()
    try:
        frontier = svc.submit_design(make_case(hours), spec,
                                     request_id="smoke-design").result(
                                         timeout=1800)
        # -- gates -----------------------------------------------------
        if frontier.frontier is None or not len(frontier.frontier):
            raise AssertionError("frontier is empty")
        if not frontier.all_finalists_certified:
            raise AssertionError(
                "not every finalist certified:\n"
                + frontier.frontier[["certified", "reason"]].to_string())
        winner = frontier.winner
        if not (1 <= int(winner["screen_rank"]) <= top_k):
            raise AssertionError(
                f"certified winner's screening rank "
                f"{winner['screen_rank']} outside top-{top_k} — the "
                "ordinal screen is not ordering the population")
        # the non-tautological ordinal-health gate (finalists are BY
        # CONSTRUCTION the screen's top-k, so the rank gate above can
        # only catch bookkeeping bugs): screening order must correlate
        # with certified order among the finalists
        corr = frontier.rank_correlation
        if corr is not None and corr < 0.5:
            raise AssertionError(
                f"screening-vs-certified rank correlation {corr} < 0.5 "
                "— the ordinal screen is not ordering this family")
        screen_dispatches = frontier.screen["dispatches"]
        n_windows = population      # one window per candidate at 72 h
        if screen_dispatches * 10 > n_windows:
            raise AssertionError(
                f"screening used {screen_dispatches} device dispatches "
                f"for {population} candidates — less than the 10x "
                "batching win over solo solves (>= 1 dispatch each)")
        cold_screen_compiles = frontier.screen["compile_events"]

        # -- warm repeat: zero compiles anywhere -----------------------
        compiles_before = svc.metrics()["rounds"]["compile_events"]
        warm = svc.submit_design(make_case(hours), spec,
                                 request_id="smoke-design-warm").result(
                                     timeout=1800)
        warm_screen_compiles = warm.screen["compile_events"]
        warm_round_compiles = (svc.metrics()["rounds"]["compile_events"]
                               - compiles_before)
        if warm_screen_compiles or warm_round_compiles:
            raise AssertionError(
                f"warm repeat compiled {warm_screen_compiles} screening "
                f"+ {warm_round_compiles} certified-round program(s) — "
                "the warm design path must compile nothing")
        if not warm.all_finalists_certified:
            raise AssertionError("warm repeat lost certification")
        m = svc.metrics()
    finally:
        svc.drain()

    print(json.dumps({
        "smoke": "design", "ok": True,
        "population": population, "top_k": top_k,
        "screen_dispatches": int(screen_dispatches),
        "solo_dispatch_floor": int(n_windows),
        "batching_win_x": round(n_windows / max(1, screen_dispatches), 1),
        "cold_screen_compile_events": int(cold_screen_compiles),
        "warm_screen_compile_events": int(warm_screen_compiles),
        "warm_round_compile_events": int(warm_round_compiles),
        "winner": {k: (float(winner[k]) if k != "certified"
                       else bool(winner[k]))
                   for k in ("kW", "kWh", "total", "screen_rank",
                             "certified")},
        "rank_correlation": frontier.rank_correlation,
        "screen_candidates_per_s":
            m["design"]["screen_candidates_per_s"],
        "design_metrics": {k: m["design"][k] for k in
                           ("requests", "candidates", "finalists",
                            "screen_rounds")},
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
