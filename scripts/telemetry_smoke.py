"""CI smoke: the telemetry plane end to end on a real 3-replica fleet.

Boots a 3-replica fleet (real ``dervet-tpu serve`` subprocesses over
file spools, CPU backend) and serves a MIXED workload — scenario
requests through :class:`~dervet_tpu.service.router.FleetRouter`, plus
a BOOST design request and a coupled-portfolio request dropped straight
into replica spools.  The telemetry contract under check:

* **every request traces** — each request (all three kinds) produced a
  ``trace.<rid>.json`` export whose stitched span set passes
  :func:`~dervet_tpu.telemetry.trace.validate_trace` (single root,
  unique ids, one trace id, no negative durations), and the routed
  scenario traces cover the full hop chain (fleet_request -> transport
  -> batch_round -> dispatch_group);
* **exposition parses** — every replica published a ``telemetry.prom``
  that :func:`~dervet_tpu.telemetry.registry.parse_prometheus` accepts,
  and the fleet-status histogram MERGE is consistent: merged count ==
  sum of per-replica counts, and the merged request-latency p50 agrees
  with the stitched traces' ``request``-span p50 within the log-bucket
  resolution (the two surfaces measure the same path independently);
* **ops CLIs work** — ``dervet-tpu status`` and ``dervet-tpu trace``
  exit 0 against the live fleet dir, and the Chrome trace-event export
  loads as JSON;
* **kill switch is real** — ``DERVET_TPU_TELEMETRY=0`` reproduces the
  full result-CSV surface BYTE-IDENTICALLY with ZERO telemetry files
  written (no trace exports, no ``telemetry.prom``).

Env knobs: SMOKE_TELEM_REQUESTS (default 4 scenario requests),
SMOKE_TELEM_DEADLINE_S (default 300).
"""
from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_REQ = int(os.environ.get("SMOKE_TELEM_REQUESTS", "4"))
DEADLINE_S = float(os.environ.get("SMOKE_TELEM_DEADLINE_S", "300"))


def log(msg: str) -> None:
    print(f"telemetry-smoke: {msg}", file=sys.stderr, flush=True)


def workload():
    """N scenario requests, one case each: DISTINCT window lengths
    (distinct LP structures) and distinct ratings (distinct content) so
    cross-request warm seeding cannot blur the byte-identity gate."""
    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    out = {}
    for i in range(N_REQ):
        case = synthetic_sensitivity_cases(1, n=72 + 24 * i, months=1)[0]
        for tag, _, keys in case.ders:
            if tag == "Battery":
                keys["ene_max_rated"] = 8000.0 + 10.0 * i
        out[f"sc{i:02d}"] = {0: case}
    return out


def write_design_request(out_dir: Path) -> Path:
    """A spool-shaped BOOST design request: a reference-format
    model-parameters CSV + its time series + the design.json that
    references them (same fixture shape the design-service tests
    serve)."""
    import pandas as pd

    from dervet_tpu.benchlib import synthetic_case
    case = synthetic_case(seed=0)
    ts = case.datasets.time_series.iloc[:72]
    ts_path = out_dir / "ts.csv"
    # the loader expects hour-ENDING stamps (it shifts back by dt)
    ts.set_axis(ts.index + pd.Timedelta(hours=1)).rename_axis(
        "Datetime (he)").to_csv(ts_path)
    rows = [
        ("Scenario", "", "dt", "1", "float"),
        ("Scenario", "", "opt_years", "[2017]", "list/int"),
        ("Scenario", "", "n", "month", "string/int"),
        ("Scenario", "", "start_year", "2017", "period"),
        ("Scenario", "", "end_year", "2017", "period"),
        ("Scenario", "", "allow_partial_year", "1", "bool"),
        ("Scenario", "", "incl_site_load", "1", "bool"),
        ("Scenario", "", "time_series_filename", str(ts_path), "string"),
        ("Finance", "", "npv_discount_rate", "7", "float"),
        ("Finance", "", "inflation_rate", "3", "float"),
        ("Battery", "1", "ch_max_rated", "1000", "float"),
        ("Battery", "1", "dis_max_rated", "1000", "float"),
        ("Battery", "1", "ene_max_rated", "4000", "float"),
        ("Battery", "1", "rte", "85", "float"),
        ("Battery", "1", "llsoc", "5", "float"),
        ("Battery", "1", "ulsoc", "100", "float"),
        ("Battery", "1", "soc_target", "50", "float"),
        ("PV", "1", "rated_capacity", "3000", "float"),
        ("PV", "1", "curtail", "1", "bool"),
        ("DA", "", "growth", "0", "float"),
    ]
    df = pd.DataFrame(rows, columns=["Tag", "ID", "Key", "Value", "Type"])
    df["Active"] = "yes"
    params_path = out_dir / "params.csv"
    df.to_csv(params_path, index=False)
    payload_path = out_dir / "design_payload.json"
    payload_path.write_text(json.dumps({"design": {
        "parameters": str(params_path),
        "der": "Battery", "kw": [500, 2000], "kwh": [1000, 8000],
        "population": 6, "top_k": 2, "refine_rounds": 0}}))
    return payload_path


PORTFOLIO_PAYLOAD = {"portfolio": {
    "synthetic_members": {"sites": 2, "hours": 48, "window": 24},
    "export_cap_kw": 5000.0,
    "gap_tol": 5e-3,
    "max_outer": 8,
}}


def drop_spool_request(spool: Path, rid: str, payload_text: str) -> None:
    """Atomically place a request file into a replica's incoming/ (the
    serve scan must never see a partial write)."""
    tmp = spool / "incoming" / f".{rid}.json.tmp"
    tmp.write_text(payload_text)
    os.replace(tmp, spool / "incoming" / f"{rid}.json")


def await_spool_result(spool: Path, rid: str, timeout: float):
    """Wait for the serve loop to finish ``rid`` (its input moves to
    done/ only after results persist + the journal's terminal record)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if (spool / "done" / f"{rid}.json").exists():
            return spool / "results" / rid
        failed = list((spool / "failed").glob(f"{rid}*"))
        assert not failed, \
            f"{rid} parked in failed/: " + \
            "; ".join(p.read_text()[:300] for p in failed
                      if p.suffix == ".txt")
        time.sleep(0.1)
    raise AssertionError(f"{rid} not served within {timeout:.0f}s")


def spawn_fleet(root: Path, tag: str, telemetry_on: bool):
    from dervet_tpu.service import spawn_replica
    env = {} if telemetry_on else {"DERVET_TPU_TELEMETRY": "0"}
    reps = []
    for i in range(3):
        name = f"{tag}{i}"
        logf = open(root / f"{name}.log", "w")
        reps.append(spawn_replica(root / name, name=name, backend="cpu",
                                  stdout=logf, stderr=logf, env=env))
    return reps


def csv_surface(results_dir: Path):
    return {p.name: p.read_bytes()
            for p in sorted(results_dir.glob("*.csv"))}


def run_pass(root: Path, tag: str, telemetry_on: bool):
    """Serve the full mixed workload on a fresh 3-replica fleet; return
    ``(csvs_by_rid, wall_by_rid)``.  The in-process router honours the
    same kill switch the replicas get via env."""
    from dervet_tpu.service import FleetRouter
    os.environ["DERVET_TPU_TELEMETRY"] = "1" if telemetry_on else "0"
    root.mkdir()
    reps = spawn_fleet(root, tag, telemetry_on)
    router = FleetRouter(reps, fleet_dir=root / "fleet",
                         heartbeat_timeout_s=5.0, tick_s=0.05).start()
    csvs, wall = {}, {}
    try:
        # the mixed tail: one design + one portfolio request straight
        # into two different replica spools (the serve scan admits them
        # exactly like router .pkl payloads)
        fixture_dir = root / "fixtures"
        fixture_dir.mkdir()
        design_payload = write_design_request(fixture_dir)
        drop_spool_request(reps[1].spool, "dsgn", design_payload.read_text())
        drop_spool_request(reps[2].spool, "pfol",
                           json.dumps(PORTFOLIO_PAYLOAD))
        t_submit = time.time()
        futs = {rid: router.submit(cases, request_id=rid,
                                   deadline_s=DEADLINE_S)
                for rid, cases in workload().items()}
        for rid, fut in futs.items():
            res = fut.result(timeout=DEADLINE_S + 60)
            wall[rid] = time.time() - t_submit
            csvs[rid] = csv_surface(res.results_dir)
        csvs["dsgn"] = csv_surface(
            await_spool_result(reps[1].spool, "dsgn", DEADLINE_S))
        csvs["pfol"] = csv_surface(
            await_spool_result(reps[2].spool, "pfol", DEADLINE_S))
        assert all(csvs.values()), \
            f"empty CSV surface: {[r for r, c in csvs.items() if not c]}"
        if telemetry_on:
            # let one more heartbeat publish the post-completion
            # registry state before the fleet goes down
            time.sleep(1.5)
    finally:
        router.close()
    return csvs, wall


def main() -> int:
    import tempfile

    workdir = Path(tempfile.mkdtemp(prefix="telemetry-smoke-"))
    report = {"scenario_requests": N_REQ, "mixed_kinds": 3}

    # ---- pass 1: telemetry OFF (the kill-switch reference) -----------
    log("pass 1: 3 replicas, DERVET_TPU_TELEMETRY=0 …")
    t0 = time.time()
    off_csvs, _ = run_pass(workdir / "off", "off", telemetry_on=False)
    report["off_wall_s"] = round(time.time() - t0, 1)

    # zero telemetry files: the kill switch writes NOTHING
    stray = [str(p) for pat in ("trace.*.json", "telemetry.prom",
                                "fleet_telemetry.prom")
             for p in (workdir / "off").rglob(pat)]
    assert not stray, f"kill switch leaked telemetry files: {stray}"
    log(f"pass 1 OK: {len(off_csvs)} requests, zero telemetry files")

    # ---- pass 2: telemetry ON ----------------------------------------
    log("pass 2: 3 replicas, telemetry on …")
    t0 = time.time()
    on_root = workdir / "on"
    on_csvs, wall = run_pass(on_root, "on", telemetry_on=True)
    report["on_wall_s"] = round(time.time() - t0, 1)

    # byte-identity: telemetry must observe, never perturb
    assert set(on_csvs) == set(off_csvs)
    for rid, ref in off_csvs.items():
        got = on_csvs[rid]
        assert sorted(got) == sorted(ref), \
            f"{rid}: CSV file set differs between telemetry on/off"
        for name in ref:
            assert got[name] == ref[name], \
                f"{rid}/{name}: bytes differ between telemetry on/off"
    log("byte-identity OK: telemetry on == off across "
        f"{sum(len(c) for c in off_csvs.values())} CSVs")

    # every request produced a valid single-root span tree
    from dervet_tpu.telemetry import trace as ttrace
    from dervet_tpu.telemetry.ops import load_stitched_trace
    n_spans = {}
    service_lat = []        # replica-side `request` span durations
    for rid in on_csvs:
        spans = load_stitched_trace(rid, [on_root])
        rep = ttrace.validate_trace(spans)
        n_spans[rid] = rep["n_spans"]
        names = {s["name"] for s in spans}
        service_lat += [s["duration_s"] for s in spans
                        if s["name"] == "request"
                        and s.get("duration_s") is not None]
        if rid.startswith("sc"):
            assert rep["root"]["name"] == "fleet_request", rep["root"]
            missing = {"transport", "batch_round",
                       "dispatch_group"} - names
            assert not missing, f"{rid}: hop chain missing {missing}"
        elif rid == "dsgn":
            assert "design_screen" in names, names
        elif rid == "pfol":
            assert "portfolio_dual_loop" in names, names
    report["spans_per_request"] = n_spans
    log(f"span trees OK: {n_spans}")

    # Prometheus expositions parse; histogram merge is consistent
    from dervet_tpu.telemetry import registry as treg
    from dervet_tpu.telemetry.ops import fleet_status
    per_replica = []
    for i in range(3):
        prom = on_root / f"on{i}" / "telemetry.prom"
        assert prom.exists(), f"replica on{i} never published {prom}"
        parsed = treg.parse_prometheus(prom.read_text())
        assert parsed, f"{prom} parsed to nothing"
        hist = treg.histogram_from_parsed(
            parsed, "dervet_request_latency_seconds")
        if hist:
            per_replica.append(hist)
    assert per_replica, "no replica published a latency histogram"
    fleet = fleet_status([on_root])
    assert fleet["n_replicas"] == 3 and fleet["n_up"] >= 1, fleet
    merged = treg.merge_histograms(per_replica)
    assert merged["count"] == sum(h["count"] for h in per_replica), \
        "histogram merge lost observations"
    # the merged count covers every request served by the fleet pass
    assert merged["count"] >= len(on_csvs), \
        f"latency histogram count {merged['count']} < " \
        f"{len(on_csvs)} served requests"
    merged_p50 = treg.quantile_from_buckets(merged, 0.5)
    p50s = [treg.quantile_from_buckets(h, 0.5) for h in per_replica]
    assert min(p50s) <= merged_p50 <= max(p50s), \
        f"merged p50 {merged_p50} outside per-replica range {p50s}"
    # agreement with the trace surface: the replica-side `request` span
    # duration is measured around the same path the histogram observes,
    # so the merged p50 must agree within the log-bucket resolution
    # (x2 buckets -> x2.5 bracket).  The router-measured wall only
    # upper-bounds it: spool transport + sibling queueing ride on top
    # and balloon under host contention.
    assert service_lat, "no replica-side request spans found"
    span_p50 = sorted(service_lat)[len(service_lat) // 2]
    assert span_p50 / 2.5 <= merged_p50 <= span_p50 * 2.5, \
        f"merged latency p50 {merged_p50:.3f}s disagrees with the " \
        f"request-span p50 {span_p50:.3f}s beyond bucket resolution"
    measured = sorted(wall.values())[len(wall) // 2]
    assert merged_p50 <= measured * 2.5, \
        f"merged latency p50 {merged_p50:.3f}s exceeds the " \
        f"router-measured wall p50 {measured:.3f}s"
    report.update({
        "latency_hist_count": merged["count"],
        "latency_hist_p50_s": round(merged_p50, 4),
        "measured_p50_s": round(measured, 4),
        "request_span_p50_s": round(span_p50, 4),
        "fleet_p50_s": fleet["latency_p50_s"],
        "slo_attainment": fleet["slo_attainment"],
    })
    log(f"exposition OK: merged p50 {merged_p50:.2f}s vs measured "
        f"{measured:.2f}s over {merged['count']} observations")

    # ops CLIs exit 0 against the live artifacts
    from dervet_tpu.telemetry.ops import status_main, trace_main
    assert status_main([str(on_root)]) == 0
    assert status_main([str(on_root), "--json"]) == 0
    sc0 = sorted(r for r in on_csvs if r.startswith("sc"))[0]
    chrome_out = workdir / "sc0.chrome.json"
    assert trace_main([sc0, str(on_root),
                       "--chrome", str(chrome_out)]) == 0
    chrome = json.loads(chrome_out.read_text())
    assert chrome.get("traceEvents"), "chrome export has no events"
    assert trace_main(["dsgn", str(on_root)]) == 0
    assert trace_main(["pfol", str(on_root)]) == 0
    log("status/trace CLIs OK")

    report["ok"] = True
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
