"""CI smoke: the sensitivity-fanout leg on the cpu backend, pipeline on.

Runs a small synthetic sensitivity fan-out through the REAL batched
dispatch pipeline (``run_dispatch(backend="jax")`` on a CPU XLA device —
no chip required) and asserts the run publishes a well-formed
``solve_ledger``: schema-checked, and with line items summing to within
10% of the measured ``dispatch_solve_s``.  This is the no-hardware
analogue of the BENCH acceptance gate on ``legs.sensitivity_fanout.
solve_ledger``, so a schema or accounting regression fails CI instead of
surfacing in the next bench artifact.

Env knobs: SMOKE_CASES (default 3), SMOKE_MONTHS (default 2).
"""
from __future__ import annotations

import json
import os
import sys
from pathlib import Path

# runnable both as `python scripts/ledger_smoke.py` from a checkout and
# against an installed package
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

# force the CPU platform BEFORE any backend is touched (same pattern as
# tests/conftest.py — some environments pre-import jax with a TPU backend)
import jax

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    from dervet_tpu.benchlib import (synthetic_sensitivity_cases,
                                     validate_solve_ledger)
    from dervet_tpu.scenario.scenario import (MicrogridScenario,
                                              run_dispatch)

    n_cases = int(os.environ.get("SMOKE_CASES", "3"))
    months = int(os.environ.get("SMOKE_MONTHS", "2"))
    os.environ[
        "DERVET_TPU_PIPELINE"] = "1"   # the smoke tests the pipeline path
    scens = [MicrogridScenario(c)
             for c in synthetic_sensitivity_cases(n_cases, months=months)]
    run_dispatch(scens, backend="jax")

    ledger = scens[0].solve_metadata["solve_ledger"]
    validate_solve_ledger(ledger)
    if ledger["pipeline"] is not True:
        raise AssertionError("pipeline was not enabled for the smoke run")
    af = ledger["accounted_fraction"]
    if af is None or abs(af - 1.0) > 0.10:
        raise AssertionError(
            f"ledger line items sum to {af} of dispatch_solve_s "
            "(acceptance: within 10%)")
    n_solved = sum(len(s.objective_values) for s in scens)
    expected = sum(len(s.windows) for s in scens)
    if n_solved != expected:
        raise AssertionError(
            f"{n_solved}/{expected} windows solved")
    print(json.dumps({
        "smoke": "solve_ledger", "ok": True, "cases": n_cases,
        "windows_solved": n_solved, "groups": len(ledger["groups"]),
        "accounted_fraction": af,
        "totals": ledger["totals"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
