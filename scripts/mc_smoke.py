"""CI smoke: the Monte-Carlo uncertainty product on cpu XLA, no chip.

Boots a :class:`~dervet_tpu.service.server.ScenarioService`
(backend="jax" on a CPU XLA device — the same no-hardware analogue the
serve/design smokes use), submits one 1024-sample Monte-Carlo valuation
request, and asserts the uncertainty contract:

* the sample mass solved through TWO dispatch rounds (screening tier +
  certified quantile-pinning tier) with the device-dispatch count far
  below one-dispatch-per-sample (the batch-axis win);
* every quantile-pinning sample carries an accepted PR-4 float64
  certificate, and the screening mass was never certificate-stamped;
* a WARM repeat of the same request compiles ZERO XLA programs
  (ledger-gated) and serializes a BYTE-IDENTICAL
  ``mc_distribution.json`` — the fixed-seed determinism contract;
* a degraded (load-shed tier) answer is marked, hints resubmission, and
  carries no certificates anywhere.

Env knobs: SMOKE_SAMPLES (default 1024), SMOKE_HOURS (default 72).
"""
from __future__ import annotations

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax

jax.config.update("jax_platforms", "cpu")


def make_case(hours: int):
    from dervet_tpu.benchlib import synthetic_case
    c = synthetic_case()
    c.scenario["allow_partial_year"] = True
    c.datasets.time_series = c.datasets.time_series.iloc[:hours]
    return c


def main() -> int:
    from dervet_tpu.service import ScenarioService
    from dervet_tpu.stochastic import MCSpec, run_montecarlo

    samples = int(os.environ.get("SMOKE_SAMPLES", "1024"))
    hours = int(os.environ.get("SMOKE_HOURS", "72"))
    spec = MCSpec(n_samples=samples, seed=7)

    svc = ScenarioService(backend="jax", max_wait_s=0.05)
    svc.start()
    try:
        res = svc.submit_montecarlo(make_case(hours), spec,
                                    request_id="smoke-mc").result(
                                        timeout=3600)
        # -- gates -----------------------------------------------------
        if res.stats["n"] < samples - res.tier_mix["quarantined"]:
            raise AssertionError(
                f"published {res.stats['n']} of {samples} samples")
        tiers = [r["tier"] for r in res.engine["rounds"]]
        if tiers != ["screening", "certified"]:
            raise AssertionError(
                f"expected one screening + one certified round, got "
                f"{tiers}")
        if not res.pinning_all_certified:
            raise AssertionError(
                "not every quantile-pinning sample certified:\n"
                + res.samples[res.samples["tier"] == "certified"][
                    ["sample", "certified", "reason"]].to_string())
        if res.engine["certification_stamped_screening"]:
            raise AssertionError(
                "a screening-tier sample was certificate-stamped — the "
                "thread-local cert-off override leaked")
        dispatches = res.engine["dispatches"]
        if dispatches * 10 > samples:
            raise AssertionError(
                f"{dispatches} device dispatches for {samples} samples "
                "— less than the 10x batching win over solo solves")
        cold_compiles = res.engine["compile_events"]

        # -- warm repeat: zero compiles, byte-identical ----------------
        warm = svc.submit_montecarlo(make_case(hours), spec,
                                     request_id="smoke-mc").result(
                                         timeout=3600)
        if warm.engine["compile_events"]:
            raise AssertionError(
                f"warm repeat compiled {warm.engine['compile_events']} "
                "program(s) — compiles must amortize to zero after "
                "round 1")
        if warm.to_json() != res.to_json():
            raise AssertionError(
                "fixed-seed warm repeat is not byte-identical")

        # -- degraded tier: never cert-stamped -------------------------
        os.environ["DERVET_TPU_MC_DEGRADED_SAMPLES"] = "64"
        shed = run_montecarlo(make_case(hours), spec, backend="jax",
                              certify_tier=False)
        if shed.fidelity != "degraded" or not shed.resubmit_hint:
            raise AssertionError("shed answer not marked degraded")
        if shed.samples["certified"].any() or \
                shed.engine["certification_stamped_screening"]:
            raise AssertionError(
                "a degraded answer carried a certificate")
        m = svc.metrics()
    finally:
        svc.drain()

    print(json.dumps({
        "smoke": "monte_carlo", "ok": True,
        "samples": samples,
        "tier_mix": res.tier_mix,
        "dispatches": int(dispatches),
        "solo_dispatch_floor": int(samples),
        "batching_win_x": round(samples / max(1, dispatches), 1),
        "cold_compile_events": int(cold_compiles),
        "warm_compile_events": int(warm.engine["compile_events"]),
        "samples_per_s_screening":
            res.engine["samples_per_s_screening"],
        "samples_per_s_certified":
            res.engine["samples_per_s_certified"],
        "stats": {k: res.stats[k] for k in
                  ("mean", "var_alpha", "cvar_alpha")},
        "mc_metrics": {k: m["monte_carlo"][k] for k in
                       ("requests", "samples", "certified_samples",
                        "quarantined")},
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
