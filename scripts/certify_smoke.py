"""CI smoke: the numerical trust layer's end-to-end recovery drill.

Runs a small synthetic sensitivity fan-out through the REAL batched
dispatch (``run_dispatch(backend="jax")`` on a CPU XLA device — no chip
required) with the ``corrupt_solution`` fault active: one window's
returned solution vector is deterministically perturbed AFTER the solver
declared success.  The drill then asserts the full trust loop closed:

* the float64 certifier REJECTED the corrupted window (``rejected`` > 0)
* the escalation ladder recovered it (``rejected_then_recovered`` > 0,
  no quarantined case)
* the final run reports 100% certified windows
  (``windows_certified`` == windows dispatched)
* the ``certification`` section of the run-health report is
  schema-valid, and the invariant audit over the assembled results
  passes

A zero exit code means every assertion held — so CI proves the
silent-wrong-answer class is caught, escalated, and recovered, not just
that the code imports.

Env knobs: SMOKE_CASES (default 3), SMOKE_MONTHS (default 2),
SMOKE_CORRUPT_WINDOW (default 1).
"""
from __future__ import annotations

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    n_cases = int(os.environ.get("SMOKE_CASES", "3"))
    months = int(os.environ.get("SMOKE_MONTHS", "2"))
    target = os.environ.get("SMOKE_CORRUPT_WINDOW", "1")
    os.environ["DERVET_TPU_FAULT_CORRUPT"] = target
    os.environ.setdefault("DERVET_TPU_FAULT_CORRUPT_SCALE", "0.05")

    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    from dervet_tpu.io.summary import run_health_report
    from dervet_tpu.ops.certify import (aggregate_audits, audit_case,
                                        validate_certification)
    from dervet_tpu.scenario.scenario import (MicrogridScenario,
                                              run_dispatch)
    from dervet_tpu.utils import faultinject

    scens = [MicrogridScenario(c)
             for c in synthetic_sensitivity_cases(n_cases, months=months)]
    run_dispatch(scens, backend="jax")     # must not raise

    plan = faultinject.get_plan()
    fired = [f for f in (plan.fired if plan else ())
             if f[0] == faultinject.EVENT_CORRUPT]
    if not fired:
        raise AssertionError(
            f"corrupt_solution fault never fired (target window {target})")

    report = run_health_report(
        {i: s.health for i, s in enumerate(scens)},
        {i: s.quarantine for i, s in enumerate(scens)
         if s.quarantine is not None},
        certification_by_case={i: s.certification
                               for i, s in enumerate(scens)})
    cert = validate_certification(report["certification"])

    quarantined = [s.case.case_id for s in scens if s.quarantine is not None]
    if quarantined:
        raise AssertionError(
            f"case(s) {quarantined} quarantined — the ladder failed to "
            "recover the corrupted window")
    if cert["windows"]["rejected"] < 1:
        raise AssertionError(
            "no certificate rejection recorded — the corruption sailed "
            "through the float64 certifier")
    if cert["windows"]["rejected_then_recovered"] < 1:
        raise AssertionError(
            "rejection was not recovered through the escalation ladder")
    if cert["windows"]["rejected_final"] != 0:
        raise AssertionError(
            f"{cert['windows']['rejected_final']} window(s) ended "
            "rejected — recovery incomplete")
    dispatched = sum(len(s.windows) for s in scens)
    if cert["windows_certified"] != dispatched:
        raise AssertionError(
            f"{cert['windows_certified']}/{dispatched} windows certified "
            "— every dispatched window must carry an accepted certificate")

    audit = aggregate_audits(
        {i: audit_case(s) for i, s in enumerate(scens)})
    if not audit["ok"]:
        raise AssertionError(
            f"invariant audit failed: {json.dumps(audit['failing'])}")

    print(json.dumps({
        "smoke": "certification", "ok": True, "cases": n_cases,
        "windows_certified": cert["windows_certified"],
        "rejected": cert["windows"]["rejected"],
        "rejected_then_recovered":
            cert["windows"]["rejected_then_recovered"],
        "cert_s": cert["cert_s"],
        "shadow": {k: cert["shadow"][k]
                   for k in ("n", "rel_diff_max", "shadow_s")},
        "corrupt_events": len(fired),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
