"""Chaos/soak harness: a seeded fault schedule against a LIVE service.

Every resilience mechanism this repo has grown — escalation ladder,
watchdog, certification, breakers, load shedding, backend-loss
recovery, poison quarantine, journal recovery — is code that only runs
when something is on fire.  This harness sets the fires on a SCHEDULE
(seeded RNG, reproducible bit for bit) and asserts the service-level
contract that CI can hold:

* **zero lost requests** — every admitted future resolves, with a
  result or a TYPED error; a raw leaked exception or an unresolved
  future fails the soak;
* **zero certified-wrong answers** — every ``fidelity: "certified"``
  result carries a 100%-certified run-health report with no final
  rejections; every degraded answer is explicitly marked and carries NO
  certificate;
* **bounded latency during degradation** — p99 over the soak stays
  under a hard bound even through hang/overload/device-loss bursts;
* **exit-0 recovery** — the service drains clean after the storm, and
  a ``dervet-tpu serve`` loop SIGKILLED mid-flight (no drain path at
  all) recovers every journaled spool request on restart with
  byte-identical result CSVs.

Phases:

1. **soak** — ``--requests N`` requests pushed through an in-process
   ``ScenarioService`` in seeded bursts; each burst draws a fault from
   {none, overload+shed, hang, corrupt_solution, device_loss,
   poison_case, deadline_expiry} through the fault-injection layer.
2. **preempt** — SIGTERM mid-round: typed preemption answers, then a
   fresh service with the same checkpoint dir + request ids resumes to
   objectives identical to an uninterrupted run.
3. **sigkill** (skippable: ``--skip-sigkill``) — a real ``serve``
   subprocess is SIGKILLED mid-spool; the restarted ``--once`` loop
   must journal-recover every request, byte-identical to an
   uninterrupted reference serve.
4. **supervised** (skippable: ``--skip-supervised``) — a 2-replica
   fleet under the lifecycle supervisor takes a SEEDED schedule of
   SIGKILL / SIGSTOP(hang) faults mid-request: zero lost requests,
   every victim respawned warm (memory import verified) at a bumped
   heartbeat epoch; a deliberately broken replica spec must crash-loop
   into the TYPED quarantined terminal state; and with
   ``DERVET_TPU_FLEET_SUPERVISE=0`` the supervisor must be a complete
   no-op (today's unsupervised fleet, bit for bit).

Usage (CI runs the first line)::

    python scripts/chaos_soak.py --seed 0 --requests 200
    python scripts/chaos_soak.py --serve-child SPOOL   # internal
"""
from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

# the chaos drills are cpu-backend by design (determinism is the whole
# point); on TPU hosts the JAX_PLATFORMS env var is ignored because the
# interpreter pre-imports jax, so force the platform the way
# tests/conftest.py does
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# the soak drills the watchdog (hang bursts): a solve deadline must be
# armed BEFORE any RunSupervisor (and its watchdog) is constructed.
# 3 s clears every honest cpu-backend group solve by a wide margin.
HANG_DEADLINE_S = 3.0
HANG_SLEEP_S = 4.0

FAULT_KINDS = ("none", "none", "none", "none", "none",
               "overload", "hang", "corrupt", "device_loss",
               "poison", "expiry")


def _cases(n: int, months: int = 1, variant: int = 0):
    """Synthetic request content.  ``variant`` nudges the battery energy
    rating so every soak request has DISTINCT content — the poison
    registry keys on content fingerprints, and identical content across
    all requests would let one quarantine blocklist the whole soak.
    (Bounds-only change: every variant still shares the compiled LP
    structure, so the hot cache keeps working.)"""
    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    cases = synthetic_sensitivity_cases(n, months=months)
    for c in cases:
        for tag, _, keys in c.ders:
            if tag == "Battery":
                keys["ene_max_rated"] = \
                    float(keys["ene_max_rated"]) + 0.001 * variant
    return {i: c for i, c in enumerate(cases)}


def log(msg: str) -> None:
    print(f"chaos: {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Phase 1: the seeded soak
# ---------------------------------------------------------------------------

def run_soak(seed: int, n_requests: int, months: int = 1,
             p99_bound_s: float = 60.0) -> dict:
    from dervet_tpu.service import (PoisonRequestError, QueueFullError,
                                    ScenarioClient, ScenarioService)
    from dervet_tpu.utils import faultinject
    from dervet_tpu.utils.errors import TypedError

    rng = random.Random(seed)
    svc = ScenarioService(backend="cpu", max_wait_s=0.0,
                          max_queue_depth=16, max_batch_requests=4,
                          shed_threshold_frac=0.5, shed_sustain_rounds=1,
                          fairness_after_s=20.0)
    client = ScenarioClient(svc, max_retries=4, jitter_seed=seed)
    futures = {}            # rid -> (future, t_submit)
    outcomes = {"completed": 0, "degraded": 0, "rejected_typed": 0,
                "failed_typed": 0}
    fault_counts = {}
    latencies = []
    submitted = 0
    burst_no = 0

    def drain_rounds(budget: int = 64) -> None:
        for _ in range(budget):
            if svc.run_once() == 0 and svc.queue.depth() == 0:
                break

    while submitted < n_requests:
        burst_no += 1
        fault = rng.choice(FAULT_KINDS)
        burst = min(1 + rng.randrange(3), n_requests - submitted)
        fault_counts[fault] = fault_counts.get(fault, 0) + burst
        rids = []
        for _ in range(burst):
            rid = f"s{submitted:05d}"
            submitted += 1
            rids.append(rid)

        def submit(rid, **kw):
            try:
                fut = client.submit(
                    _cases(1, months, variant=len(futures)),
                    request_id=rid, **kw)
                futures[rid] = (fut, time.monotonic())
            except (QueueFullError, PoisonRequestError) as e:
                # typed fast rejection IS an answered request
                outcomes["rejected_typed"] += 1
                futures[rid] = (e, time.monotonic())

        if fault == "none":
            for rid in rids:
                submit(rid, priority=rng.randrange(3))
            drain_rounds()
        elif fault == "overload":
            # flood past the shed threshold: low-priority requests get
            # degraded screening answers, high-priority stay certified;
            # a couple of injected queue-full rejections drill the
            # client's capped+jittered retry discipline
            with faultinject.inject(overload=True, overload_n=1):
                for k, rid in enumerate(rids):
                    submit(rid, priority=k % 2)
            extra = [f"s{submitted + j:05d}x" for j in range(10)]
            for k, rid in enumerate(extra):
                submit(rid, priority=k % 2)
            drain_rounds()
        elif fault == "hang":
            # one solve call sleeps past the watchdog deadline: the call
            # is abandoned, counted, and the windows recover downstream
            for rid in rids:
                submit(rid)
            with faultinject.inject(hang="all",
                                    hang_seconds=HANG_SLEEP_S):
                svc.run_once()
            drain_rounds()
        elif fault == "corrupt":
            # solver says OPTIMAL, numbers are wrong: only the float64
            # certifier can catch it; the ladder must recover and the
            # final answer must still be 100% certified
            for rid in rids:
                submit(rid)
            with faultinject.inject(corrupt="all", corrupt_scale=0.05):
                svc.run_once()
            drain_rounds()
        elif fault == "device_loss":
            for rid in rids:
                submit(rid)
            with faultinject.inject(device_loss=True,
                                    device_loss_n=1):
                drain_rounds()
        elif fault == "poison":
            # first request of the burst is poisonous: its dispatch
            # crashes every attempt; co-batched innocents must complete
            bad = rids[0]
            for rid in rids:
                submit(rid)
            with faultinject.inject(crash_cases={f"{bad}.0"}):
                drain_rounds()
        elif fault == "expiry":
            for rid in rids:
                submit(rid, deadline_s=1e-9)
            time.sleep(0.01)
            drain_rounds()

    drain_rounds(budget=256)

    # ---- the contract ------------------------------------------------
    lost = []
    for rid, (fut_or_err, t0) in futures.items():
        if not hasattr(fut_or_err, "done"):
            continue                    # typed admission rejection
        fut = fut_or_err
        if not fut.done():
            lost.append(rid)
            continue
        err = fut.exception()
        if err is None:
            res = fut.result()
            latencies.append(res.request_latency_s or 0.0)
            cert = res.run_health["certification"]
            n_win = sum(len(inst.scenario.windows)
                        for inst in res.instances.values())
            if res.fidelity == "certified":
                outcomes["completed"] += 1
                assert cert["enabled"], f"{rid}: cert disabled on a " \
                    "certified-fidelity result"
                assert cert["windows_certified"] == n_win, \
                    f"{rid}: {cert['windows_certified']}/{n_win} certified"
                assert cert["windows"]["rejected_final"] == 0, \
                    f"{rid}: final certificate rejections"
            else:
                outcomes["degraded"] += 1
                assert res.fidelity == "degraded", res.fidelity
                assert res.resubmit_hint, f"{rid}: degraded without hint"
                assert res.run_health["fidelity"] == "degraded"
                assert cert["windows_certified"] == 0, \
                    f"{rid}: degraded answer carries certificates"
        else:
            assert isinstance(err, TypedError), \
                f"{rid}: RAW error leaked to the client: {err!r}"
            outcomes["failed_typed"] += 1
    assert not lost, f"lost requests (unresolved futures): {lost}"

    latencies.sort()
    p99 = latencies[int(0.99 * (len(latencies) - 1))] if latencies else 0.0
    assert p99 <= p99_bound_s, \
        f"p99 {p99:.1f}s exceeds the {p99_bound_s:g}s degradation bound"

    svc.drain()                         # exit-0 analogue: raises nothing
    m = svc.metrics()
    answered = sum(outcomes.values())
    assert answered == len(futures), (answered, len(futures))
    return {
        "requests": len(futures),
        "outcomes": outcomes,
        "faults": fault_counts,
        "latency_p50_s": round(latencies[len(latencies) // 2], 3)
        if latencies else None,
        "latency_p99_s": round(p99, 3),
        "resilience": m["resilience"],
        "queue": {k: m["queue"][k]
                  for k in ("admitted", "rejected_full",
                            "rejected_overload", "expired",
                            "fairness_promotions")},
    }


# ---------------------------------------------------------------------------
# Phase 2: preempt mid-round, typed answers, resume-identical
# ---------------------------------------------------------------------------

def run_preempt_drill(workdir: Path) -> dict:
    from dervet_tpu.api import DERVET
    from dervet_tpu.service import (RequestPreemptedError,
                                    ScenarioService)
    from dervet_tpu.utils import faultinject
    from dervet_tpu.utils.errors import PreemptedError

    ckpt = workdir / "preempt-ckpt"
    ref = DERVET.from_cases(_cases(2, months=2)).solve(backend="cpu")

    svc = ScenarioService(backend="cpu", max_wait_s=0.0,
                          checkpoint_dir=ckpt)
    fut = svc.submit(_cases(2, months=2), request_id="pre")
    preempted = False
    with svc.supervisor:
        with faultinject.inject(preempt_after=1):
            try:
                svc.run_once()
            except PreemptedError:
                preempted = True
    assert preempted, "preempt fault did not fire"
    err = fut.exception(0)
    assert isinstance(err, RequestPreemptedError), err

    svc2 = ScenarioService(backend="cpu", max_wait_s=0.0,
                           checkpoint_dir=ckpt)
    fut2 = svc2.submit(_cases(2, months=2), request_id="pre")
    assert svc2.run_once() == 1
    res = fut2.result(0)
    for k in ref.instances:
        a = ref.instances[k].scenario.objective_values
        b = res.instances[k].scenario.objective_values
        assert a == b, f"resumed case {k} diverged from uninterrupted run"
    svc2.close()
    svc.close()
    return {"preempted": True, "resumed_identical": True}


# ---------------------------------------------------------------------------
# Phase 3: SIGKILL a real serve loop, journal-recover byte-identical
# ---------------------------------------------------------------------------

N_SPOOL = 6


def _spawn_serve(spool: Path, once: bool, slow: bool) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if slow:
        # slow every solve so the SIGKILL reliably lands mid-spool
        env.update(DERVET_TPU_FAULT_SLOW="all",
                   DERVET_TPU_FAULT_SLOW_S="0.5")
    cmd = [sys.executable, str(Path(__file__).resolve()),
           "--serve-child", str(spool)]
    if once:
        cmd.append("--child-once")
    return subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def serve_child(spool: str, once: bool) -> int:
    """Internal: a real serve loop over synthetic inputs (model-params
    parsing patched out — the chaos drill targets the SERVING machinery,
    and the container has no reference data set)."""
    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    from dervet_tpu.io import params as params_mod

    def fake_initialize(cls, path, base_path=None, verbose=False):
        return {0: synthetic_sensitivity_cases(1, months=1)[0]}

    params_mod.Params.initialize = classmethod(fake_initialize)
    from dervet_tpu.service.server import serve_main
    argv = [str(spool), "--backend", "cpu", "--poll-s", "0.05"]
    if once:
        argv.append("--once")
    return serve_main(argv)


def run_sigkill_drill(workdir: Path) -> dict:
    # reference: an uninterrupted --once serve of the same spool inputs
    ref_spool = workdir / "ref-spool"
    kill_spool = workdir / "kill-spool"
    for spool in (ref_spool, kill_spool):
        (spool / "incoming").mkdir(parents=True)
        for i in range(N_SPOOL):
            (spool / "incoming" / f"req{i}.csv").write_text("synthetic")
    proc = _spawn_serve(ref_spool, once=True, slow=False)
    assert proc.wait(timeout=600) == 0, "reference serve failed"

    # kill run: serve loop (no --once), SIGKILL once the first request
    # has fully landed in done/ — no drain path runs at all
    proc = _spawn_serve(kill_spool, once=False, slow=True)
    deadline = time.monotonic() + 300
    try:
        while not list((kill_spool / "done").glob("*.csv")):
            assert proc.poll() is None, "serve child died early"
            assert time.monotonic() < deadline, "no progress before kill"
            time.sleep(0.05)
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=60)
    killed_done = len(list((kill_spool / "done").glob("*.csv")))
    log(f"sigkill: killed serve loop with {killed_done}/{N_SPOOL} "
        "request(s) completed")

    # restart: --once must journal-recover and serve EVERYTHING
    proc = _spawn_serve(kill_spool, once=True, slow=False)
    assert proc.wait(timeout=600) == 0, "restarted serve failed"

    recovered = 0
    for i in range(N_SPOOL):
        rid = f"req{i}"
        assert (kill_spool / "done" / f"{rid}.csv").exists(), \
            f"{rid}: input file not retired after recovery"
        ref_dir = ref_spool / "results" / rid
        got_dir = kill_spool / "results" / rid
        ref_csvs = sorted(p.name for p in ref_dir.glob("*.csv"))
        got_csvs = sorted(p.name for p in got_dir.glob("*.csv"))
        assert ref_csvs == got_csvs and ref_csvs, \
            f"{rid}: result CSV set differs after recovery"
        for name in ref_csvs:
            assert (ref_dir / name).read_bytes() == \
                (got_dir / name).read_bytes(), \
                f"{rid}/{name}: recovered bytes differ from " \
                "uninterrupted serve"
        recovered += 1

    from dervet_tpu.service import ServiceJournal
    journal = ServiceJournal(kill_spool / "service_journal.jsonl")
    unfinished = journal.unfinished()
    journal.close()
    assert not unfinished, f"journal still has unfinished: {unfinished}"
    return {"killed_with_done": killed_done, "recovered": recovered,
            "byte_identical": True}


# ---------------------------------------------------------------------------
# Phase 4: the supervised fleet under a seeded fault schedule
# ---------------------------------------------------------------------------

def _sup_wait(pred, timeout: float, msg: str) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(msg)


def run_supervised_drill(workdir: Path, seed: int) -> dict:
    """Seeded SIGKILL/SIGSTOP schedule against a LIVE supervised fleet:
    zero lost requests, every victim healed warm at a bumped epoch, the
    crash-looping spec quarantined with a typed state, and the
    ``DERVET_TPU_FLEET_SUPERVISE=0`` kill switch a complete no-op."""
    from dervet_tpu.service import (FleetRouter, FleetSupervisor,
                                    ReplicaSpec, ServiceJournal)

    rng = random.Random(seed ^ 0x5F1EE7)
    rounds = 2
    schedule = [rng.choice(("sigkill", "hang")) for _ in range(rounds)]
    log(f"supervised: seeded fault schedule {schedule}")

    root = workdir / "supervised"
    # replicas inherit this process's env (which armed the soak's tight
    # solve deadline); the per-solve slow fault sleeps OUTSIDE the
    # solver, but give the children the default generous deadline back
    env = {"DERVET_TPU_FAULT_SLOW": "all",
           "DERVET_TPU_FAULT_SLOW_S": "0.4",
           "DERVET_TPU_SOLVE_DEADLINE_S": "",
           "DERVET_TPU_REQUEST_CACHE": "0"}
    specs = [ReplicaSpec(root / f"r{i}", name=f"r{i}", backend="cpu",
                         env=env) for i in range(2)]
    router = FleetRouter([], fleet_dir=root / "fleet",
                         heartbeat_timeout_s=3.0, tick_s=0.05,
                         breaker_opts={"min_samples": 1,
                                       "failure_threshold": 0.5,
                                       "cooldown_s": 1.0}).start()
    sup = FleetSupervisor(router, specs, backoff_base_s=0.2, tick_s=0.1)
    assert sup.enabled, "supervision disabled in the soak environment"
    sup.start()
    expected_restarts = {"r0": 0, "r1": 0}
    fired = []
    delivered = 0
    try:
        _sup_wait(lambda: all(sup.snapshot()["replicas"][s.name]["state"]
                              == "up" for s in specs),
                  240, "supervised fleet never came up")
        from dervet_tpu.benchlib import synthetic_sensitivity_cases
        for rnd, fault in enumerate(schedule):
            futs = {}
            for i in range(4):
                # distinct window lengths per request: distinct LP
                # structures, so affinity cannot pin the whole round to
                # one replica and both stay in the fault's blast radius
                case = synthetic_sensitivity_cases(
                    1, n=72 + 24 * (4 * rnd + i), months=1)[0]
                rid = f"sup{rnd}.{i}"
                futs[rid] = router.submit(
                    {0: case}, request_id=rid, deadline_s=300.0)

            # the victim is whichever replica is genuinely mid-request
            # with a warm export to hand off; the seeded order breaks
            # ties so the drill stays reproducible
            order = rng.sample(["r0", "r1"], 2)
            victim_name = None

            def mid_request():
                nonlocal victim_name
                for nm in order:
                    h = router.replicas.get(nm)
                    if h is None or h.process is None or \
                            h.alive() is not True:
                        continue
                    states = ServiceJournal.replay_path(
                        h.spool / "service_journal.jsonl")
                    if any(e["state"] == "admitted"
                           for e in states.values()) and \
                            (h.spool / "memory_export.pkl").exists():
                        victim_name = nm
                        return True
                return False

            _sup_wait(mid_request, 240,
                      f"round {rnd}: no replica mid-request with a "
                      "warm export — fault window missed")
            h = router.replicas[victim_name]
            if fault == "sigkill":
                h.process.send_signal(signal.SIGKILL)
            else:
                os.kill(h.process.pid, signal.SIGSTOP)
            log(f"supervised round {rnd}: {fault} on {victim_name} "
                "mid-request")
            fired.append([victim_name, fault])
            expected_restarts[victim_name] += 1

            for rid, fut in futs.items():
                res = fut.result(timeout=600)
                assert res is not None, f"{rid}: lost"
                delivered += 1

            want_epoch = 1 + expected_restarts[victim_name]

            def healed():
                hh = router.replicas.get(victim_name)
                if hh is None or hh.process is None \
                        or hh.alive() is not True:
                    return False
                rec = sup.snapshot()["replicas"][victim_name]
                return (rec["state"] == "up"
                        and rec["restarts"]
                        >= expected_restarts[victim_name]
                        and int(hh.epoch or 0) >= want_epoch
                        and router.metrics()["replicas"][victim_name]
                        ["breaker"]["state"] == "closed")

            _sup_wait(healed, 240,
                      f"round {rnd}: {victim_name} never healed")
            rec = sup.snapshot()["replicas"][victim_name]
            assert rec["warm_imports"] >= 1, \
                f"round {rnd}: {victim_name} respawned cold"
            log(f"supervised round {rnd}: {victim_name} healed "
                f"(epoch {router.replicas[victim_name].epoch}, "
                f"warm imports {rec['warm_imports']})")

        m = router.metrics()["routing"]
        snap = sup.snapshot()
        assert m["failed"] == 0, m
        assert m["completed"] == delivered == 4 * rounds, m
        assert snap["counters"]["restarts"] >= rounds, snap["counters"]
        assert snap["counters"]["warm_imports"] >= rounds, \
            snap["counters"]
        assert snap["counters"]["quarantined"] == 0, snap["counters"]
    finally:
        sup.stop()
        router.close()

    # -- quarantine sub-drill: a spec that can only crash-loop ---------
    broken_root = workdir / "supervised-broken"
    broken = ReplicaSpec(broken_root / "bad", name="bad", backend="cpu",
                         extra_args=["--definitely-not-a-flag"])
    router2 = FleetRouter([], fleet_dir=broken_root / "fleet",
                          heartbeat_timeout_s=1.0, tick_s=0.05).start()
    sup2 = FleetSupervisor(router2, [broken], backoff_base_s=0.05,
                           backoff_max_s=0.2, rapid_crash_window_s=30.0,
                           quarantine_after=2, tick_s=0.05)
    sup2.start()
    try:
        _sup_wait(lambda: sup2.snapshot()["replicas"]["bad"]["state"]
                  == "quarantined", 240, "broken spec never quarantined")
        q = sup2.snapshot()["replicas"]["bad"]["quarantine"]
        assert q["kind"] == "replica_quarantined", q
        assert q["crashes"] >= 2, q
        n_restarts = sup2.snapshot()["counters"]["restarts"]
        time.sleep(0.5)
        assert sup2.snapshot()["counters"]["restarts"] == n_restarts, \
            "quarantine is not terminal — still respawning"
        log(f"supervised: broken spec quarantined after {q['crashes']} "
            "rapid crashes (typed, terminal)")
    finally:
        sup2.stop()
        router2.close()

    # -- kill switch: DERVET_TPU_FLEET_SUPERVISE=0 is a full no-op -----
    prev = os.environ.get("DERVET_TPU_FLEET_SUPERVISE")
    os.environ["DERVET_TPU_FLEET_SUPERVISE"] = "0"
    try:
        off_root = workdir / "supervised-off"
        router3 = FleetRouter([], fleet_dir=off_root / "fleet",
                              tick_s=0.05).start()
        sup3 = FleetSupervisor(
            router3, [ReplicaSpec(off_root / "r0", name="r0")])
        sup3.start()
        try:
            assert not sup3.enabled
            assert router3.supervisor is None, \
                "kill switch left the supervisor attached"
            assert sup3._thread is None
            sup3.on_replica_dead("r0", "crash")
            time.sleep(0.2)
            assert "r0" not in router3.replicas, \
                "kill switch still spawned a replica"
            assert not (off_root / "fleet" /
                        "supervisor_state.json").exists(), \
                "kill switch still published supervisor state"
        finally:
            sup3.stop()
            router3.close()
    finally:
        if prev is None:
            os.environ.pop("DERVET_TPU_FLEET_SUPERVISE", None)
        else:
            os.environ["DERVET_TPU_FLEET_SUPERVISE"] = prev

    return {"schedule": schedule, "fired": fired,
            "delivered": delivered, "lost": 0,
            "restarts": dict(expected_restarts),
            "quarantine": {"kind": q["kind"], "crashes": q["crashes"]},
            "kill_switch_noop": True}


# ---------------------------------------------------------------------------

def main() -> int:
    parser = argparse.ArgumentParser(
        description="seeded chaos/soak drill for the scenario service")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--requests", type=int, default=200)
    parser.add_argument("--months", type=int, default=1)
    parser.add_argument("--skip-sigkill", action="store_true",
                        help="skip the subprocess SIGKILL phase")
    parser.add_argument("--skip-preempt", action="store_true")
    parser.add_argument("--skip-supervised", action="store_true",
                        help="skip the supervised-fleet lifecycle phase")
    parser.add_argument("--workdir", default=None,
                        help="scratch dir (default: a fresh tempdir)")
    parser.add_argument("--serve-child", default=None,
                        help=argparse.SUPPRESS)
    parser.add_argument("--child-once", action="store_true",
                        help=argparse.SUPPRESS)
    args = parser.parse_args()

    if args.serve_child:
        return serve_child(args.serve_child, args.child_once)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # arm the watchdog BEFORE any service/supervisor is built (the hang
    # bursts rely on it); generous vs honest cpu group solves
    os.environ[
        "DERVET_TPU_SOLVE_DEADLINE_S"] = str(HANG_DEADLINE_S)

    import tempfile
    workdir = Path(args.workdir or tempfile.mkdtemp(prefix="chaos-soak-"))
    workdir.mkdir(parents=True, exist_ok=True)

    t0 = time.time()
    report = {"seed": args.seed}
    log(f"soak: {args.requests} seeded requests …")
    report["soak"] = run_soak(args.seed, args.requests,
                              months=args.months)
    if not args.skip_preempt:
        log("preempt drill …")
        report["preempt"] = run_preempt_drill(workdir)
    if not args.skip_sigkill:
        log("sigkill drill …")
        report["sigkill"] = run_sigkill_drill(workdir)
    if not args.skip_supervised:
        log("supervised-fleet drill …")
        report["supervised"] = run_supervised_drill(workdir, args.seed)
    report["elapsed_s"] = round(time.time() - t0, 1)
    report["ok"] = True
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
