"""Micro-profile of the PDHG chunk loop on the bench's largest group.

Times (a) one full run_chunk of `chunk_iters` on the T=744 group at the
bench batch size, (b) a bare batched matvec pair at the same shapes, to
separate MXU GEMM cost from elementwise/state overhead.
"""
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dervet_tpu.benchlib import build_window_lps, scenario_price_batch, synthetic_case
from dervet_tpu.ops.pdhg import CompiledLPSolver, PDHGOptions, op_matvec, op_rmatvec

B = int(os.environ.get("PROF_B", "7000"))
ITERS = int(os.environ.get("PROF_ITERS", "1024"))

case = synthetic_case()
scen, groups = build_window_lps(case)
T = max(groups)
lp = groups[T][0]
print(f"group T={T}: n={lp.n} m={lp.m}, batch {B}", file=sys.stderr)

opts = PDHGOptions(chunk_iters=ITERS)
solver = CompiledLPSolver(lp, opts)
C = scenario_price_batch(lp, B)
c, q, l, u = solver.batch_data(B, *solver._data(C, None, None, None))
args = (solver.op, c, q, l, u, solver.dr, solver.dc)

state = solver._jit_init_b(*args)
jax.block_until_ready(state.x)

# warm-up compile
st = solver._jit_chunk_b(*args, solver.eta, state, np.int32(ITERS))
jax.block_until_ready(st.x)

t0 = time.time()
st2 = solver._jit_chunk_b(*args, solver.eta, st, np.int32(2 * ITERS))
jax.block_until_ready(st2.x)
dt_chunk = time.time() - t0
per_iter = dt_chunk / ITERS
print(f"chunk: {dt_chunk:.3f}s for {ITERS} iters -> {per_iter*1e3:.3f} ms/iter")

# bare matvec pair at same shapes
x = jnp.asarray(np.random.rand(B, lp.n), jnp.float32)
prec = opts.precision


@jax.jit
def mv_pair(x):
    y = jax.vmap(lambda v: op_matvec(solver.op, v, prec))(x)
    return jax.vmap(lambda w: op_rmatvec(solver.op, w, prec))(y)


r = mv_pair(x)
jax.block_until_ready(r)
t0 = time.time()
N = 50
for _ in range(N):
    x = mv_pair(x)
jax.block_until_ready(x)
per_mv = (time.time() - t0) / N
print(f"bare matvec+rmatvec: {per_mv*1e3:.3f} ms/pair "
      f"({100*per_mv/per_iter:.0f}% of loop iter)")
flops = 2 * 2 * B * lp.m * lp.n
print(f"GEMM tflops at that rate: {flops/per_mv/1e12:.1f}")
