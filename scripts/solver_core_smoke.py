"""CI smoke: the solver-core leap (step variants + learned seeding) on
the cpu XLA backend, no chip.

Two stages:

**Variant stage** (direct solver, fixed case set): one monthly dispatch
window, a fixed batch of perturbed-price instances, solved cold under
``variant='vanilla'`` and under the product default — gates a >= 30%
median cold-iteration reduction from the step variant ALONE, with every
instance converged under both.

**Service stage** (full serving path): a ScenarioService serves

1. a COLD request (baseline; trains the warm-start memory and the seed
   predictor, compiles the whole program family);
2. a PERTURBED request (same structures, ~1% different data — the
   structure-repeat cold shape): gates 100% certification, ZERO compile
   events (no new shapes on a warm service), and at least one
   ``predicted``-grade seed in the round ledger;
3. the same perturbed shape again under an injected ``stale_seed``
   fault (the corrupted-prediction fault-matrix row): the corrupted
   seeds must still converge and certify 100%, with the faults
   attributed in the ledger (``warm.stale_seed_faults``) — a bad
   prediction costs iterations, never correctness.

Env knobs: SMOKE_CASES (default 4), SMOKE_MONTHS (default 1),
SMOKE_BATCH (default 8).
"""
from __future__ import annotations

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def variant_stage(batch: int) -> dict:
    """Median cold-iteration reduction, vanilla -> default variant,
    plus the halpern-native restart drill: under its fixed-point-
    residual schedule halpern must actually RESTART (anchor resets > 0)
    and land within 15% of reflected median cold iterations — the gap
    the PDLP weighted-average schedule left open (PR 11)."""
    from dervet_tpu.benchlib import build_window_lps, synthetic_case
    from dervet_tpu.ops.pdhg import (CompiledLPSolver, PDHGOptions,
                                     resolved_variant)

    case = synthetic_case()
    _, groups = build_window_lps(case)
    lp0 = sorted(groups.items())[0][1][0]
    rng = np.random.default_rng(0)
    C = np.stack([lp0.c * (1 + 0.05 * rng.standard_normal(lp0.c.shape))
                  for _ in range(batch)])

    out = {}
    for label, opts in (("vanilla", PDHGOptions(variant="vanilla")),
                        ("variant", PDHGOptions()),
                        ("halpern", PDHGOptions(variant="halpern"))):
        solver = CompiledLPSolver(lp0, opts)
        res = solver.solve(c=C)
        it = np.asarray(res.iters)
        conv = int(np.asarray(res.converged).sum())
        if conv != batch:
            raise AssertionError(
                f"{label}: only {conv}/{batch} instances converged")
        out[label] = {"iters_p50": int(np.percentile(it, 50)),
                      "iters_p99": int(np.percentile(it, 99)),
                      "variant": resolved_variant(opts),
                      "restart_scheme": solver.restart_scheme,
                      "restarts": int(np.asarray(res.restarts).sum())}
    red = 1.0 - out["variant"]["iters_p50"] / out["vanilla"]["iters_p50"]
    out["reduction"] = round(red, 4)
    if red < 0.30:
        raise AssertionError(
            f"variant-alone cold-iteration reduction {red:.1%} < 30% "
            f"(vanilla p50 {out['vanilla']['iters_p50']}, "
            f"{out['variant']['variant']} p50 "
            f"{out['variant']['iters_p50']})")
    # the halpern-native FP-residual restart criterion must ENGAGE
    # (restarts recorded under the fixed_point scheme)...
    if out["halpern"]["restart_scheme"] != "fixed_point":
        raise AssertionError(
            "halpern did not resolve to the fixed_point restart scheme: "
            f"{out['halpern']}")
    if out["halpern"]["restarts"] <= 0:
        raise AssertionError(
            f"halpern FP-residual restarts never engaged: {out['halpern']}")
    # ...and close halpern's standalone gap to within 15% of reflected
    ratio = out["halpern"]["iters_p50"] / max(out["variant"]["iters_p50"],
                                              1)
    out["halpern_vs_reflected"] = round(ratio, 4)
    if ratio > 1.15:
        raise AssertionError(
            f"halpern standalone p50 {out['halpern']['iters_p50']} is "
            f"{ratio:.2f}x reflected's {out['variant']['iters_p50']} "
            "(> 1.15x): the FP-residual schedule is not closing the gap")
    return out


def _assert_certified(res, n_windows: int, label: str) -> None:
    cert = res.run_health["certification"]
    if not cert["enabled"] or cert["windows_certified"] != n_windows \
            or cert["windows"]["rejected_final"]:
        raise AssertionError(f"{label}: not 100% certified: {cert}")


def service_stage(n_cases: int, months: int) -> dict:
    from dervet_tpu.benchlib import (synthetic_sensitivity_cases,
                                     validate_solve_ledger)
    from dervet_tpu.service import ScenarioService
    from dervet_tpu.utils import faultinject

    def perturbed(scale):
        fam = synthetic_sensitivity_cases(n_cases, months=months)
        for c in fam:
            for tag, _, keys in c.ders:
                if tag == "Battery":
                    keys["ene_max_rated"] *= scale
        return {i: c for i, c in enumerate(fam)}

    svc = ScenarioService(backend="jax", max_wait_s=0.0)
    svc.start()
    try:
        cold_res = svc.submit(perturbed(1.0),
                              request_id="sc-cold").result(timeout=600)
        cold_led = svc.last_round_ledger
        warm_res = svc.submit(perturbed(1.01),
                              request_id="sc-warm").result(timeout=600)
        warm_led = svc.last_round_ledger
        with faultinject.inject(stale_seed={"all"}):
            fault_res = svc.submit(perturbed(1.02),
                                   request_id="sc-fault").result(
                                       timeout=600)
        fault_led = svc.last_round_ledger
        metrics = svc.metrics()
    finally:
        svc.drain()

    validate_solve_ledger(warm_led)
    n_windows = sum(len(inst.scenario.windows)
                    for inst in warm_res.instances.values())
    _assert_certified(cold_res, n_windows, "cold pass")
    _assert_certified(warm_res, n_windows, "perturbed pass")
    _assert_certified(fault_res, n_windows, "fault pass")

    warm = warm_led.get("warm_start") or {}
    if int(warm_led["totals"]["compile_events"]):
        raise AssertionError(
            f"perturbed pass compiled "
            f"{warm_led['totals']['compile_events']} program(s) — the "
            "variant/seeded program family must be part of the cold "
            "round's warm-up (no new shapes on a warm service)")
    if not warm.get("predicted"):
        raise AssertionError(
            f"perturbed pass served no predicted-grade seeds: {warm}")
    core = warm_led.get("solver_core") or {}
    if not core.get("variants"):
        raise AssertionError(f"no solver_core section in ledger: {core}")
    if not core.get("restart_schemes"):
        raise AssertionError(
            f"no restart_schemes mix in the ledger solver_core: {core}")

    fault_warm = fault_led.get("warm_start") or {}
    if not fault_warm.get("stale_seed_faults"):
        raise AssertionError(
            "corrupted-prediction pass recorded no stale_seed faults: "
            f"{fault_warm}")

    cold_p50 = (cold_led.get("warm_start") or {}).get("iters_p50_cold") \
        or cold_led["iters"]["p50"]
    return {
        "windows": n_windows,
        "iters_p50_cold": int(cold_p50),
        "perturbed": {
            "iters_p50_seeded": warm.get("iters_p50_seeded"),
            "iters_p50_predicted": warm.get("iters_p50_predicted"),
            "predicted": warm.get("predicted"),
            "compile_events": int(warm_led["totals"]["compile_events"]),
        },
        "fault": {
            "stale_seed_faults": fault_warm.get("stale_seed_faults"),
            "iters_p50_seeded": fault_warm.get("iters_p50_seeded"),
        },
        "solver_core": core,
        "memory": metrics["warm_start"],
    }


def main() -> int:
    n_cases = int(os.environ.get("SMOKE_CASES", "4"))
    months = int(os.environ.get("SMOKE_MONTHS", "1"))
    batch = int(os.environ.get("SMOKE_BATCH", "8"))
    out = {"smoke": "solver_core", "ok": True,
           "variant_stage": variant_stage(batch),
           "service_stage": service_stage(n_cases, months)}
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
