"""CI smoke: the supervised fleet self-heals under SIGKILL + hang.

Boots a 2-replica fleet under :class:`~dervet_tpu.service.lifecycle.
FleetSupervisor` (real ``dervet-tpu serve`` subprocesses over file
spools, CPU backend) and runs two drills against it:

* **kill drill** — SIGKILL one replica mid-request.  The router fences
  and re-routes its in-flight work (exactly-once), then the supervisor
  respawns the name at a bumped heartbeat epoch with the dead
  incarnation's warm-start memory imported, and the replacement earns
  routing back through the breaker's probe cycle.
* **hang drill** — SIGSTOP the other replica mid-request (heartbeats
  freeze: indistinguishable from a wedged process).  The router
  declares it dead, fence-kills it, re-routes, and the supervisor
  heals it the same way.

The contract: **zero lost requests** (every future resolves, nothing
double-delivered), **both replicas healed** (respawned at epoch 2 with
a verified warm memory import, back in the routable set), and the
supervisor's counters/state file record the whole story.

Env knobs: SMOKE_LIFECYCLE_REQUESTS (default 6 per wave),
SMOKE_LIFECYCLE_DEADLINE_S (default 300), SMOKE_LIFECYCLE_SLOW_S
(default 0.5 — per-solve injected delay so the faults land mid-round).
"""
from __future__ import annotations

import json
import os
import signal
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# this smoke drills the replica lifecycle: repeats must reach replicas,
# not the router's memoization plane
os.environ["DERVET_TPU_REQUEST_CACHE"] = "0"

N_REQ = int(os.environ.get("SMOKE_LIFECYCLE_REQUESTS", "6"))
DEADLINE_S = float(os.environ.get("SMOKE_LIFECYCLE_DEADLINE_S", "300"))
SLOW_S = os.environ.get("SMOKE_LIFECYCLE_SLOW_S", "0.5")


def log(msg: str) -> None:
    print(f"lifecycle-smoke: {msg}", file=sys.stderr, flush=True)


def workload(tag: str):
    """N single-case requests with distinct window lengths (distinct LP
    structures, so routing spreads them) and distinct content."""
    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    out = {}
    for i in range(N_REQ):
        case = synthetic_sensitivity_cases(1, n=72 + 24 * i, months=1)[0]
        for der_tag, _, keys in case.ders:
            if der_tag == "Battery":
                keys["ene_max_rated"] = 8000.0 + 10.0 * i
        out[f"{tag}{i:02d}"] = {0: case}
    return out


def route_wave(router, reqs):
    return {rid: router.submit(cases, request_id=rid,
                               deadline_s=DEADLINE_S)
            for rid, cases in reqs.items()}


def collect(futs, timeout=600):
    return {rid: fut.result(timeout=timeout) for rid, fut in futs.items()}


def _wait(pred, timeout, msg):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(msg)


def pick_victim(router, name):
    """Wait until NAME holds >= 1 admitted (unfinished) request and has
    published a warm-start export — a kill now is mid-request and the
    replacement has a blob to import."""
    from dervet_tpu.service import ServiceJournal

    def ready():
        h = router.replicas.get(name)
        if h is None or h.process is None:
            return False
        states = ServiceJournal.replay_path(
            h.spool / "service_journal.jsonl")
        inflight = sum(1 for e in states.values()
                       if e["state"] == "admitted")
        return inflight >= 1 and \
            (h.spool / "memory_export.pkl").exists()

    _wait(ready, 240, f"{name}: no admitted in-flight request + warm "
                      "export before the wave drained — fault window "
                      "missed")
    return router.replicas[name]


def wait_healed(router, sup, name, *, epoch, restarts):
    """The replacement is routable again: fresh beats at the bumped
    epoch, breaker closed by the probe cycle, supervisor record UP."""
    def healed():
        h = router.replicas.get(name)
        if h is None or h.process is None or h.alive() is not True:
            return False
        m = router.metrics()["replicas"].get(name, {})
        rec = sup.snapshot()["replicas"].get(name, {})
        return (m.get("state") == "up"
                and m.get("breaker", {}).get("state") == "closed"
                and rec.get("state") == "up"
                and int(h.epoch or 0) >= epoch)

    _wait(healed, 240, f"{name}: never healed to a routable epoch-"
                       f"{epoch} replacement")
    snap = sup.snapshot()
    rec = snap["replicas"][name]
    assert rec["restarts"] >= restarts, rec
    assert rec["warm_imports"] >= 1, \
        f"{name}: replacement respawned cold (no memory import)"
    assert rec["last_restart_reason"], rec
    log(f"{name} healed: epoch {router.replicas[name].epoch}, "
        f"restarts {rec['restarts']}, warm imports "
        f"{rec['warm_imports']} ({rec['last_restart_reason']})")


def main() -> int:
    import tempfile

    from dervet_tpu.service import FleetRouter, FleetSupervisor, ReplicaSpec

    workdir = Path(tempfile.mkdtemp(prefix="lifecycle-smoke-"))
    report = {"requests_per_wave": N_REQ}
    env = {"DERVET_TPU_FAULT_SLOW": "all",
           "DERVET_TPU_FAULT_SLOW_S": SLOW_S}
    specs = [ReplicaSpec(workdir / f"r{i}", name=f"r{i}", backend="cpu",
                         env=env)
             for i in range(2)]
    router = FleetRouter([], fleet_dir=workdir / "fleet",
                         heartbeat_timeout_s=3.0, tick_s=0.05,
                         breaker_opts={"min_samples": 1,
                                       "failure_threshold": 0.5,
                                       "cooldown_s": 1.0}).start()
    sup = FleetSupervisor(router, specs, backoff_base_s=0.2,
                          tick_s=0.1)
    assert sup.enabled, "supervision disabled in the environment"
    sup.start()
    try:
        _wait(lambda: all(sup.snapshot()["replicas"][s.name]["state"]
                          == "up" for s in specs),
              240, "supervised fleet never came up")
        log("2-replica supervised fleet up")

        # ---- kill drill: SIGKILL r0 mid-request ----------------------
        futs = route_wave(router, workload("kill."))
        victim = pick_victim(router, "r0")
        pid = victim.process.pid
        victim.process.send_signal(signal.SIGKILL)
        log(f"SIGKILLed r0 (pid {pid}) mid-request")
        results = collect(futs)
        assert len(results) == N_REQ, "lost requests in the kill drill"
        wait_healed(router, sup, "r0", epoch=2, restarts=1)

        # ---- hang drill: SIGSTOP r1 mid-request ----------------------
        futs2 = route_wave(router, workload("hang."))
        victim = pick_victim(router, "r1")
        pid = victim.process.pid
        os.kill(pid, signal.SIGSTOP)
        log(f"SIGSTOPed r1 (pid {pid}) mid-request — heartbeats frozen")
        results2 = collect(futs2)
        assert len(results2) == N_REQ, "lost requests in the hang drill"
        wait_healed(router, sup, "r1", epoch=2, restarts=1)

        # ---- the contract --------------------------------------------
        m = router.metrics()
        r = m["routing"]
        assert r["completed"] == 2 * N_REQ, r
        assert r["failed"] == 0, r
        assert r["failovers"] >= 2, r
        snap = sup.snapshot()
        assert snap["counters"]["restarts"] >= 2, snap["counters"]
        assert snap["counters"]["warm_imports"] >= 2, snap["counters"]
        assert snap["counters"]["quarantined"] == 0, snap["counters"]
        state_doc = json.loads(
            (workdir / "fleet" / "supervisor_state.json").read_text())
        assert state_doc["replicas"]["r0"]["restarts"] >= 1
        assert state_doc["replicas"]["r1"]["restarts"] >= 1
        report.update({
            "restarts": snap["counters"]["restarts"],
            "warm_imports": snap["counters"]["warm_imports"],
            "completed": r["completed"],
            "failovers": r["failovers"],
            "rerouted": r["rerouted"],
            "harvested": r["harvested"],
            "duplicates_suppressed": r["duplicates_suppressed"],
            "epochs": {n: router.replicas[n].epoch
                       for n in ("r0", "r1")},
        })
    finally:
        sup.stop()
        router.close()

    report["ok"] = True
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
