"""Independent-formulation cross-check for goldenless stream families.

VERDICT r5 #5: the cpu-vs-jax parity sweep proves the SOLVER, not the
model — both backends consume the same ``ops/lp.py`` output, so a shared
LP-assembly bug (sign slip, off-by-one recurrence, mis-indexed headroom
row) passes every parity gate.  The stream families with no reference
golden (FR/SR/NSR/LF, DR, User) have no external executable spec either:
the reference's semantics live in the missing StorageVET layer.

This module is the independent re-assembly: each window's dispatch LP is
built a SECOND time from the SURVEY §2.8 semantics with a deliberately
different stack — flat index arithmetic + scipy COO triplets solved by
``scipy.optimize.linprog`` (HiGHS), no ``LPBuilder``, no named blocks,
different variable ordering (ch, dis, ene, bids) — and the optimal
window objective is asserted equal to the product path's
``objective_values['Total Objective']``.  Two equivalent LPs share their
optimum even when the argmin is degenerate, so the check is exact
(~1e-6 relative) wherever the formulations agree.

Families covered: FR (001), SR (006), NSR (005), DR day-ahead (015),
User (011) from reference inputs; LF, EV1, and VoltVar synthesized from
000 (the snapshot ships no input for those three) — every family
VERDICT r5 #5 names.

Run directly (prints one line per case) or through
``tests/test_crosscheck.py`` (``--runslow``).
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd
import scipy.sparse as sp
from scipy.optimize import linprog

REF = Path("/root/reference")
MP = REF / "test/test_storagevet_features/model_params"

CASES = {
    "FR": "001-DA_FR_battery_month.csv",
    "SR": "006-DA_SR_battery_month.csv",
    "NSR": "005-DA_NSR_battery_month.csv",
    "DR": "015-DA_DRdayahead_battery_month.csv",
    "User": "011-DA_User_battery_month.csv",
    "LF": None,                      # synthesized, see make_lf_case()
    "EV1": None,                     # synthesized, see make_ev1_case()
    "Volt": None,                    # synthesized, see make_volt_case()
}


# ---------------------------------------------------------------------------
# independent window model
# ---------------------------------------------------------------------------

def _col(ts: pd.DataFrame, name: str) -> Optional[np.ndarray]:
    lower = {c.strip().lower(): c for c in ts.columns}
    c = lower.get(name.strip().lower())
    return None if c is None else ts[c].to_numpy(dtype=np.float64)


def _battery_params(case) -> Dict[str, float]:
    (tag, der_id, keys), = [d for d in case.ders if d[0] == "Battery"]
    g = lambda k, d=0.0: float(keys.get(k, d) or 0.0)
    E = g("ene_max_rated")
    return dict(
        rte=g("rte", 100.0) / 100.0,
        sdr=g("sdr") / 100.0,
        e_lo=g("llsoc") / 100.0 * E,
        e_hi=g("ulsoc", 100.0) / 100.0 * E,
        e_tgt=g("soc_target", 50.0) / 100.0 * E,
        ch_cap=g("ch_max_rated"),
        dis_cap=g("dis_max_rated"),
        daily_cycle=g("daily_cycle_limit"),
        usable=(g("ulsoc", 100.0) - g("llsoc")) / 100.0 * E,
        var_om=g("OMexpenses") / 1000.0,
        fixed_om=g("fixedOM"),
        hp=g("hp"),          # house power: constant kW load
    )


def _dr_event_mask(case, index: pd.DatetimeIndex) -> np.ndarray:
    """Top-`days` site-load days per active DR month, program hours only
    (independent re-derivation of the DR day-ahead event selection)."""
    keys = case.streams["DR"]
    days = int(float(keys.get("days", 0) or 0))
    weekend = bool(keys.get("weekend", False))
    start = float(keys.get("program_start_hour"))
    end = keys.get("program_end_hour")
    length = keys.get("length")

    def num(v):
        try:
            f = float(v)
            return None if np.isnan(f) else f
        except (TypeError, ValueError):
            return None

    end, length = num(end), num(length)
    if end is None:
        end = start + length - 1
    monthly = case.datasets.monthly
    he = np.asarray(index.hour) + 1
    hours = (he >= start) & (he <= end)
    if not weekend:
        hours &= np.asarray(index.weekday) < 5
    ym = list(zip(index.year, index.month))
    if "DR Months (y/n)" in monthly.columns:
        act = monthly["DR Months (y/n)"]
        active = np.array([float(act.get((y, m), 0) or 0) > 0
                           for y, m in ym])
    else:
        active = np.ones(len(index), bool)
    in_prog = hours & active
    site = _col(case.datasets.time_series.loc[index], "Site Load (kW)")
    load = site if site is not None else np.ones(len(index))
    mask = np.zeros(len(index), bool)
    dates = np.asarray(index.date)
    for (y, m) in sorted(set(ym)):
        sel = (np.asarray(index.year) == y) & (np.asarray(index.month) == m) \
            & in_prog
        if not sel.any():
            continue
        day_max: Dict[object, float] = {}
        for d_, v, s_ in zip(dates, load, sel):
            if s_:
                day_max[d_] = max(day_max.get(d_, -np.inf), v)
        top = sorted(day_max, key=day_max.get, reverse=True)[:days]
        mask |= sel & np.isin(dates, top)
    return mask


def independent_window_objective(case, index: pd.DatetimeIndex) -> float:
    """Optimal objective of one window, re-derived from SURVEY §2.8.

    Variable layout (deliberately different from the product's):
      x = [ch(T), dis(T), ene(T), bid_0(T), ..., ev_ch(T)?]
    """
    ts = case.datasets.time_series.loc[index]
    dt = float(case.scenario.get("dt", 1) or 1)
    T = len(index)
    bp = _battery_params(case)
    da_price = _col(ts, "DA Price ($/kWh)")

    ev_keys = next((k for t, _i, k in case.ders
                    if t == "ElectricVehicle1"), None)

    # VoltVar: per-step real-power derate of inverter caps,
    # P <= cap * sqrt(1 - (r/100)^2)
    derate = np.ones(T)
    if "Volt" in case.streams:
        r = np.clip(np.asarray(_col(ts, "VAR Reservation (%)")) / 100.0,
                    0.0, 1.0)
        derate = np.sqrt(np.maximum(1.0 - r ** 2, 0.0))

    # fixed site load (POI: incl_site_load, no ControllableLoad DER here)
    # + DER fixed loads (battery house power)
    load = np.full(T, bp["hp"])
    if bool(case.scenario.get("incl_site_load", False)):
        site = _col(ts, "Site Load (kW)")
        if site is not None:
            load += site

    # --- service bid columns --------------------------------------------
    # (tag, direction, price array, throughput array, duration,
    #  lb array | None, ub array | None)
    bids: List[tuple] = []
    combined: List[Tuple[int, int]] = []

    def ts_bounds(keys, enabled_key, stem):
        """Optional per-step bid bounds from '<stem> Max/Min (kW)'."""
        if not bool(keys.get(enabled_key, False)):
            return None, None
        hi = _col(ts, f"{stem} Max (kW)")
        lo = _col(ts, f"{stem} Min (kW)")
        if lo is not None:
            lo = np.maximum(lo, 0.0)
        return lo, hi

    for tag, keys in sorted(case.streams.items()):
        if tag not in ("FR", "SR", "NSR", "LF"):
            continue
        dur = float(keys.get("duration", 0) or 0)
        if tag == "FR":
            eou = float(keys.get("eou", 0) or 0)
            eod = float(keys.get("eod", 0) or 0)
            if bool(keys.get("CombinedMarket", False)) and \
                    _col(ts, "FR Price ($/kW)") is not None:
                pu = pd_ = _col(ts, "FR Price ($/kW)")
            else:
                pu = _col(ts, "Reg Up Price ($/kW)")
                pd_ = _col(ts, "Reg Down Price ($/kW)")
            i0 = len(bids)
            lo_u, hi_u = ts_bounds(keys, "u_ts_constraints", "FR Reg Up")
            lo_d, hi_d = ts_bounds(keys, "d_ts_constraints", "FR Reg Down")
            bids.append(("FR", "up", pu, np.full(T, eou), dur, lo_u, hi_u))
            bids.append(("FR", "down", pd_, np.full(T, eod), dur,
                         lo_d, hi_d))
            if bool(keys.get("CombinedMarket", False)):
                combined.append((i0, i0 + 1))
        elif tag == "LF":
            ku = _col(ts, "LF Energy Option Up (kWh/kW-hr)")
            kd = _col(ts, "LF Energy Option Down (kWh/kW-hr)")
            lo_u, hi_u = ts_bounds(keys, "u_ts_constraints", "LF Reg Up")
            lo_d, hi_d = ts_bounds(keys, "d_ts_constraints", "LF Reg Down")
            bids.append(("LF", "up", _col(ts, "LF Up Price ($/kW)"),
                         ku if ku is not None else np.zeros(T), dur,
                         lo_u, hi_u))
            bids.append(("LF", "down", _col(ts, "LF Down Price ($/kW)"),
                         kd if kd is not None else np.zeros(T), dur,
                         lo_d, hi_d))
        elif tag == "SR":
            lo, hi = ts_bounds(keys, "ts_constraints", "SR")
            bids.append(("SR", "up", _col(ts, "SR Price ($/kW)"),
                         np.zeros(T), dur, lo, hi))
        elif tag == "NSR":
            lo, hi = ts_bounds(keys, "ts_constraints", "NSR")
            bids.append(("NSR", "up", _col(ts, "NSR Price ($/kW)"),
                         np.zeros(T), dur, lo, hi))

    nb = len(bids)
    n = 3 * T + nb * T + (T if ev_keys is not None else 0)
    CH, DIS, ENE = 0, T, 2 * T
    EV = 3 * T + nb * T              # EV charge block, when present

    def bid_off(i):
        return 3 * T + i * T

    # --- objective -------------------------------------------------------
    c = np.zeros(n)
    const = float(np.sum(da_price * load)) * dt          # DA cost of load
    c[CH:CH + T] += da_price * dt                        # import costs
    c[DIS:DIS + T] += -da_price * dt                     # export earns
    c[DIS:DIS + T] += bp["var_om"] * dt
    const += bp["fixed_om"] * bp["dis_cap"] * (T * dt) / 8760.0
    # the product tilts each service's optimization price by
    # TIEBREAK_EPS x rank for a unique split between co-priced streams;
    # mirrored here so window objectives stay comparable.  The constants
    # are imported, not copied — independence is of the LP CONSTRUCTION,
    # and a silently desynchronized epsilon would fail every co-priced
    # input with an error blaming assembly (review r5)
    from dervet_tpu.models.streams.markets import MarketService
    rank = MarketService.TIEBREAK_RANK
    eps = MarketService.TIEBREAK_EPS
    for i, (tag, direction, price, k, dur, _lo, _hi) in enumerate(bids):
        o = bid_off(i)
        tilt = 1.0 - eps * rank.get(tag, 0)
        c[o:o + T] += -price * dt * tilt                 # capacity revenue
        sign = -1.0 if direction == "up" else +1.0       # energy settlement
        c[o:o + T] += sign * k * da_price * dt

    # --- bounds ----------------------------------------------------------
    lb = np.zeros(n)
    ub = np.full(n, np.inf)
    ub[CH:CH + T] = bp["ch_cap"] * derate
    ub[DIS:DIS + T] = bp["dis_cap"] * derate
    lb[ENE:ENE + T] = bp["e_lo"]
    ub[ENE:ENE + T] = bp["e_hi"]
    if ev_keys is not None:
        g = lambda k, d=0.0: float(ev_keys.get(k, d) or 0.0)
        hours = np.asarray(index.hour)
        t_in, t_out = int(g("plugin_time")), int(g("plugout_time"))
        plugged = ((hours >= t_in) & (hours < t_out)) if t_in <= t_out \
            else ((hours >= t_in) | (hours < t_out))
        ub[EV:EV + T] = np.where(plugged, g("ch_max_rated"), 0.0)
        c[EV:EV + T] += da_price * dt        # EV charging is a load
    for i, (_t, _d, _p, _k, _dur, blo, bhi) in enumerate(bids):
        o = bid_off(i)
        if blo is not None:
            lb[o:o + T] = blo
        if bhi is not None:
            ub[o:o + T] = bhi

    rows: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []  # (r, c, v)
    rhs_eq: List[np.ndarray] = []
    nrow = 0

    def add(r, cc, v):
        rows.append((np.asarray(r, int), np.asarray(cc, int),
                     np.asarray(v, float)))

    # --- SOE equalities (begin-of-step) ---------------------------------
    # row 0: ene[0] = e_tgt;  row t: ene[t] - (1-sdr) ene[t-1]
    #                                 - rte dt ch[t-1] + dt dis[t-1] = 0
    t_ = np.arange(1, T)
    add([0], [ENE], [1.0])
    add(t_, ENE + t_, np.ones(T - 1))
    add(t_, ENE + t_ - 1, -np.full(T - 1, 1.0 - bp["sdr"]))
    add(t_, CH + t_ - 1, -np.full(T - 1, bp["rte"] * dt))
    add(t_, DIS + t_ - 1, np.full(T - 1, dt))
    b_eq_vals = np.zeros(T)
    b_eq_vals[0] = bp["e_tgt"]
    rhs_eq.append(b_eq_vals)
    nrow += T
    # post-window state pinned back to target
    add([nrow], [ENE + T - 1], [1.0 - bp["sdr"]])
    add([nrow], [CH + T - 1], [bp["rte"] * dt])
    add([nrow], [DIS + T - 1], [-dt])
    rhs_eq.append(np.array([bp["e_tgt"]]))
    nrow += 1
    # combined market: up == down, per timestep
    for iu, idn in combined:
        r = np.arange(nrow, nrow + T)
        add(r, bid_off(iu) + np.arange(T), np.ones(T))
        add(r, bid_off(idn) + np.arange(T), -np.ones(T))
        rhs_eq.append(np.zeros(T))
        nrow += T
    # EV1 session energy: each plugged session FULLY inside the window
    # must deliver ene_target (independent re-derivation: sessions
    # touching either window boundary carry no equality)
    if ev_keys is not None:
        sid = np.zeros(T, np.int64)
        s_ = 0
        prev = False
        for t, p in enumerate(plugged):
            if p and not prev:
                s_ += 1
            sid[t] = s_ if p else 0
            prev = p
        for s_no in range(1, s_ + 1):
            idx_s = np.nonzero(sid == s_no)[0]
            if (idx_s[0] == 0 and plugged[0]) or \
                    (idx_s[-1] == T - 1 and plugged[-1]):
                continue
            add(np.full(len(idx_s), nrow), EV + idx_s,
                np.full(len(idx_s), dt))
            rhs_eq.append(np.array([float(ev_keys.get("ene_target", 0)
                                          or 0)]))
            nrow += 1
    n_eq = nrow

    # --- inequalities (A_ub x <= b_ub) ----------------------------------
    ub_rows: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
    b_ub: List[np.ndarray] = []
    nub = 0

    def add_ub(r, cc, v):
        ub_rows.append((np.asarray(r, int), np.asarray(cc, int),
                        np.asarray(v, float)))

    # daily cycle limit:  dt * sum_day dis <= limit * usable
    if bp["daily_cycle"] > 0:
        codes, uniq = pd.factorize(index.normalize())
        r = nub + codes
        add_ub(r, DIS + np.arange(T), np.full(T, dt))
        b_ub.append(np.full(len(uniq),
                            bp["daily_cycle"] * bp["usable"]))
        nub += len(uniq)

    # joint headroom:  up: sum bids + dis - ch <= dis_cap
    #                  down: sum bids + ch - dis <= ch_cap
    for direction, pcol, pcap in (("up", DIS, bp["dis_cap"]),
                                  ("down", CH, bp["ch_cap"])):
        idxs = [i for i, b_ in enumerate(bids) if b_[1] == direction]
        if not idxs:
            continue
        r = nub + np.arange(T)
        for i in idxs:
            add_ub(r, bid_off(i) + np.arange(T), np.ones(T))
        add_ub(r, pcol + np.arange(T), np.ones(T))
        other = CH if pcol == DIS else DIS
        add_ub(r, other + np.arange(T), -np.ones(T))
        b_ub.append(np.full(T, pcap))
        nub += T

    # POI interconnection limits:
    # max_import <= dis - ch - ev_ch - load <= max_export
    if bool(case.scenario.get("apply_interconnection_constraints", False)):
        max_exp = float(case.scenario.get("max_export", 0) or 0)
        max_imp = float(case.scenario.get("max_import", 0) or 0)
        for sgn, lim in ((1.0, max_exp), (-1.0, -max_imp)):
            r = nub + np.arange(T)
            add_ub(r, DIS + np.arange(T), np.full(T, sgn))
            add_ub(r, CH + np.arange(T), np.full(T, -sgn))
            if ev_keys is not None:
                add_ub(r, EV + np.arange(T), np.full(T, -sgn))
            b_ub.append(np.full(T, lim) + sgn * load)
            nub += T

    # SOE reservation: up: ene - sum dur*bid >= e_lo   (as <=: -ene + ... )
    #                  down: ene + sum dur*bid <= e_hi
    up_d = [(i, b_[4]) for i, b_ in enumerate(bids)
            if b_[1] == "up" and b_[4]]
    if up_d:
        r = nub + np.arange(T)
        add_ub(r, ENE + np.arange(T), -np.ones(T))
        for i, dur in up_d:
            add_ub(r, bid_off(i) + np.arange(T), np.full(T, dur))
        b_ub.append(np.full(T, -bp["e_lo"]))
        nub += T
    dn_d = [(i, b_[4]) for i, b_ in enumerate(bids)
            if b_[1] == "down" and b_[4]]
    if dn_d:
        r = nub + np.arange(T)
        add_ub(r, ENE + np.arange(T), np.ones(T))
        for i, dur in dn_d:
            add_ub(r, bid_off(i) + np.arange(T), np.full(T, dur))
        b_ub.append(np.full(T, bp["e_hi"]))
        nub += T

    # --- system requirements (User columns, DR day-ahead) ---------------
    reqs: List[Tuple[str, str, np.ndarray]] = []
    if "User" in case.streams:
        exp = _col(ts, "POI: Max Export (kW)")
        if exp is not None:
            reqs.append(("poi export", "max", exp))
        imp = _col(ts, "POI: Max Import (kW)")
        if imp is not None:
            reqs.append(("poi export", "min", imp))
        emax = _col(ts, "Aggregate Energy Max (kWh)")
        if emax is not None:
            reqs.append(("energy", "max", emax))
        emin = _col(ts, "Aggregate Energy Min (kWh)")
        if emin is not None:
            reqs.append(("energy", "min", emin))
    if "DR" in case.streams and bool(case.streams["DR"].get("day_ahead")):
        monthly = case.datasets.monthly
        cap_m = monthly["DR Capacity (kW)"] if "DR Capacity (kW)" in \
            monthly.columns else None
        cap = np.array([float(cap_m.get((y, m), 0) or 0) if cap_m is not None
                        else 0.0 for y, m in zip(index.year, index.month)])
        mask = _dr_event_mask(case, index)
        reqs.append(("discharge", "min", np.where(mask, cap, 0.0)))

    for kind, sense, arr in reqs:
        arr = np.asarray(arr, float)
        if not np.isfinite(arr).any():
            continue
        lo_fill = -1e30 if kind == "poi export" else 0.0
        arr = np.where(np.isfinite(arr), arr,
                       lo_fill if sense == "min" else 1e30)
        sgn = 1.0 if sense == "max" else -1.0     # encode as <=
        r = nub + np.arange(T)
        if kind == "energy":
            add_ub(r, ENE + np.arange(T), np.full(T, sgn))
            b_ub.append(sgn * arr)
        elif kind == "discharge":
            add_ub(r, DIS + np.arange(T), np.full(T, sgn))
            b_ub.append(sgn * arr)
        elif kind == "poi export":
            # net export = dis - ch - ev_ch - load
            add_ub(r, DIS + np.arange(T), np.full(T, sgn))
            add_ub(r, CH + np.arange(T), np.full(T, -sgn))
            if ev_keys is not None:
                add_ub(r, EV + np.arange(T), np.full(T, -sgn))
            b_ub.append(sgn * (arr + load))
        nub += T

    # --- assemble + solve ------------------------------------------------
    def coo(parts, m):
        if not parts:
            return sp.csr_matrix((m, n))
        r = np.concatenate([p[0] for p in parts])
        cc = np.concatenate([p[1] for p in parts])
        v = np.concatenate([p[2] for p in parts])
        return sp.coo_matrix((v, (r, cc)), shape=(m, n)).tocsr()

    A_eq = coo(rows, n_eq)
    b_eqv = np.concatenate(rhs_eq) if rhs_eq else np.zeros(0)
    A_ub = coo(ub_rows, nub)
    b_ubv = np.concatenate(b_ub) if b_ub else np.zeros(0)
    res = linprog(c, A_ub=A_ub, b_ub=b_ubv, A_eq=A_eq, b_eq=b_eqv,
                  bounds=np.stack([lb, ub], axis=1), method="highs")
    if res.status != 0:
        raise RuntimeError(f"independent model failed: {res.message}")
    return float(res.fun) + const


# ---------------------------------------------------------------------------
# product-path comparison
# ---------------------------------------------------------------------------

def make_lf_case():
    """Synthesize an LF case from 000 (the snapshot ships no LF input)."""
    from dervet_tpu.io.params import Params
    cases = Params.initialize(MP / "000-DA_battery_month.csv", base_path=REF)
    case = cases[0]
    ts = case.datasets.time_series
    rng = np.random.default_rng(42)
    ts["LF Up Price ($/kW)"] = rng.uniform(1, 8, len(ts)).round(2)
    ts["LF Down Price ($/kW)"] = rng.uniform(1, 8, len(ts)).round(2)
    ts["LF Energy Option Up (kWh/kW-hr)"] = \
        rng.uniform(0.05, 0.3, len(ts)).round(3)
    ts["LF Energy Option Down (kWh/kW-hr)"] = \
        rng.uniform(0.05, 0.3, len(ts)).round(3)
    case.streams["LF"] = {"growth": 0, "duration": 0.5,
                          "CombinedMarket": False}
    return case


def make_ev1_case():
    """Battery + DA + a single plug-session EV (no reference EV input)."""
    from dervet_tpu.io.params import Params
    cases = Params.initialize(MP / "000-DA_battery_month.csv", base_path=REF)
    case = cases[0]
    case.ders.append(("ElectricVehicle1", "1", {
        "name": "ev1", "ch_max_rated": 50, "ch_min_rated": 0,
        "ene_target": 80, "plugin_time": 19, "plugout_time": 7}))
    return case


def make_volt_case():
    """Battery + DA + VoltVar reactive-power reservation."""
    from dervet_tpu.io.params import Params
    cases = Params.initialize(MP / "000-DA_battery_month.csv", base_path=REF)
    case = cases[0]
    ts = case.datasets.time_series
    rng = np.random.default_rng(7)
    ts["VAR Reservation (%)"] = rng.uniform(0, 60, len(ts)).round(1)
    case.streams["Volt"] = {}
    return case


def crosscheck_case(family: str, max_windows: int = 12) -> float:
    """Run the product path and the independent model; return the worst
    relative window-objective mismatch."""
    from dervet_tpu.io.params import Params
    from dervet_tpu.scenario.scenario import MicrogridScenario

    if family == "LF":
        case = make_lf_case()
    elif family == "EV1":
        case = make_ev1_case()
    elif family == "Volt":
        case = make_volt_case()
    else:
        cases = Params.initialize(MP / CASES[family], base_path=REF)
        case = cases[0]
    # LP-vs-LP comparison: the binary on/off path has its own exact-MILP
    # tests (tests/test_binary.py); here the target is stream assembly
    case.scenario["binary"] = 0
    s = MicrogridScenario(case)
    s.optimize_problem_loop(backend="cpu")
    worst = 0.0
    for ctx in s.windows[:max_windows]:
        got = s.objective_values[ctx.label]["Total Objective"]
        want = independent_window_objective(case, ctx.index)
        rel = abs(got - want) / max(1.0, abs(want))
        worst = max(worst, rel)
    return worst


def main() -> int:
    bad = 0
    for family in CASES:
        try:
            worst = crosscheck_case(family)
            ok = worst < 1e-5
            print(f"crosscheck[{family}]: worst window-objective rel err "
                  f"{worst:.2e} -> {'OK' if ok else 'MISMATCH'}")
            bad += not ok
        except Exception as e:   # noqa: BLE001 - report every family
            print(f"crosscheck[{family}]: ERROR {e}")
            bad += 1
    return bad


if __name__ == "__main__":
    raise SystemExit(main())
