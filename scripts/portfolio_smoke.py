"""CI smoke: portfolio co-optimization on the cpu XLA backend, no chip.

Boots a :class:`~dervet_tpu.service.server.ScenarioService`, serves an
UNCONSTRAINED 16-site probe (round 0 of the dual loop IS the
independent solve — it also yields the fleet's unconstrained aggregate
export profile), then a BINDING shared-export-cap portfolio, and gates
the portfolio acceptance contract:

* the dual loop converges within the outer-iteration budget with the
  duality gap below the spec tolerance;
* 100% of the member sites' final-iterate windows carry an accepted
  float64 certificate, and the float64 portfolio certificate
  (coupling-row feasibility + Lagrangian gap) accepts;
* ZERO XLA compile events after outer round 1 (the dual loop re-solves
  the same structures at shifted prices — round 1 onward must ride the
  compiled programs of round 0);
* dual-iterate warm seeding engaged on every round >= 1 window;
* the ledger/metrics ``portfolio`` section schema-validates.

Env knobs: SMOKE_SITES (default 16), SMOKE_HOURS (336),
SMOKE_WINDOW (168).
"""
from __future__ import annotations

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    from dervet_tpu.portfolio import (PortfolioSpec,
                                      validate_portfolio_section)
    from dervet_tpu.ops.certify import validate_portfolio_certification
    from dervet_tpu.portfolio.service import synthetic_portfolio_members
    from dervet_tpu.service import ScenarioService

    n_sites = int(os.environ.get("SMOKE_SITES", "16"))
    hours = int(os.environ.get("SMOKE_HOURS", "336"))
    window = int(os.environ.get("SMOKE_WINDOW", "168"))

    def members():
        return synthetic_portfolio_members(n_sites, hours=hours,
                                           window=window)

    svc = ScenarioService(backend="jax", max_wait_s=0.0)
    svc.start()
    try:
        # unconstrained probe: round 0 == the independent fleet solve;
        # its aggregate profile sets a genuinely binding cap
        probe = svc.submit_portfolio(
            PortfolioSpec(members=members(), export_cap_kw=1e9,
                          max_outer=1),
            request_id="pf-probe").result(timeout=1800)
        cap = float(probe.aggregate["net_export"].max()) \
            - 500.0 * n_sites
        spec = PortfolioSpec(members=members(), export_cap_kw=cap,
                             max_outer=12)
        res = svc.submit_portfolio(spec, request_id="pf-bind").result(
            timeout=1800)
        metrics = svc.metrics()
    finally:
        svc.drain()

    section = metrics["portfolio"]["last"]
    validate_portfolio_section(section)
    validate_portfolio_certification(res.certification)

    n_windows = res.certification["per_site"]["windows_total"]

    # gate 1: converged within the outer budget, gap below tolerance
    if not res.converged or res.outer_rounds > spec.max_outer:
        raise AssertionError(
            f"dual loop did not converge in {spec.max_outer} rounds "
            f"(gap {res.gap_rel:.3e})")
    if res.gap_rel > spec.gap_tol:
        raise AssertionError(
            f"duality gap {res.gap_rel:.3e} above tolerance "
            f"{spec.gap_tol:g}")

    # gate 2: 100% per-site certified + portfolio certificate accepted
    ps = res.certification["per_site"]
    if not ps["all_certified"] or res.certification["verdict"] not in (
            "certified", "certified_loose"):
        raise AssertionError(
            f"portfolio not fully certified: {res.certification}")

    # gate 3: zero compile events after outer round 1
    late_compiles = sum(int(r["compile_events"])
                        for r in res.rounds[1:])
    if late_compiles:
        raise AssertionError(
            f"{late_compiles} XLA compile(s) after outer round 1 — the "
            "dual loop must reuse round 0's programs")

    # gate 4: dual-iterate reseeding (or exact substitution) carried
    # EVERY window of every later round — a silent fall-back to the
    # feature/predicted grades would keep `seeded` nonzero while the
    # dedicated dual-loop grade this PR exists for is broken
    for r in res.rounds[1:]:
        if r["seeded"] < r["windows"] or \
                r["dual_iterate"] + r["substituted"] < r["windows"]:
            raise AssertionError(
                f"round {r['round']}: dual-iterate reseeding did not "
                f"carry all {r['windows']} windows: {r}")

    binding = res.certification["coupling_rows"]["export_cap"]["binding"]
    print(json.dumps({
        "smoke": "portfolio", "ok": True,
        "sites": n_sites, "windows": n_windows,
        "outer_rounds": res.outer_rounds,
        "gap_rel": res.gap_rel,
        "binding_rows": binding,
        "verdict": res.certification["verdict"],
        "rounds": [{k: r[k] for k in
                    ("round", "iters_p50", "seeded", "dual_iterate",
                     "substituted", "compile_events", "gap_rel")}
                   for r in res.rounds],
        "dual_iterate_seeds_total":
            metrics["portfolio"]["dual_iterate_seeds"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
