"""CI smoke: the scenario service on the cpu XLA backend, no chip.

Boots a :class:`~dervet_tpu.service.server.ScenarioService`
(backend="jax" on a CPU XLA device — the same no-hardware analogue the
ledger smoke uses), pushes N concurrent mixed-size requests through the
continuous batcher from worker threads, and asserts the serving
contract: every request completes, 100% of windows carry an accepted
float64 certificate, the round ledger is schema-valid, cross-request
coalescing actually happened, a warm repeat round compiles NOTHING, and
the drain exits cleanly (exit code 0).

Env knobs: SMOKE_REQUESTS (default 4), SMOKE_MONTHS (default 1).
"""
from __future__ import annotations

import json
import os
import sys
import threading
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    from dervet_tpu.benchlib import (synthetic_sensitivity_cases,
                                     validate_solve_ledger)
    from dervet_tpu.service import ScenarioService

    n_req = int(os.environ.get("SMOKE_REQUESTS", "4"))
    months = int(os.environ.get("SMOKE_MONTHS", "1"))

    svc = ScenarioService(backend="jax", max_wait_s=0.25)
    svc.start()
    futs = {}
    lock = threading.Lock()

    def submit(i: int) -> None:
        # mixed sizes, submitted from concurrent clients so admission +
        # coalescing run the real multi-threaded path
        cases = synthetic_sensitivity_cases(1 + i % 3, months=months)
        fut = svc.submit({k: c for k, c in enumerate(cases)},
                         request_id=f"smoke{i}")
        with lock:
            futs[f"smoke{i}"] = fut

    threads = [threading.Thread(target=submit, args=(i,))
               for i in range(n_req)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    total_windows = 0
    for rid, fut in sorted(futs.items()):
        res = fut.result(timeout=600)
        cert = res.run_health["certification"]
        n_windows = sum(len(inst.scenario.windows)
                        for inst in res.instances.values())
        total_windows += n_windows
        if not cert["enabled"]:
            raise AssertionError(f"{rid}: certification disabled")
        if cert["windows_certified"] != n_windows:
            raise AssertionError(
                f"{rid}: {cert['windows_certified']}/{n_windows} windows "
                "certified (acceptance: 100%)")
        if cert["windows"]["rejected_final"]:
            raise AssertionError(f"{rid}: final certificate rejections")
        sl = res.solve_ledger
        if sl is None or sl["totals"]["windows"] != n_windows:
            raise AssertionError(f"{rid}: bad ledger slice {sl}")

    # round-level ledger: schema-valid, and the batches genuinely mixed
    # requests (the whole point of the continuous batcher).  The
    # coalescing count is CUMULATIVE (service metrics) so a request mix
    # that split across rounds still proves itself.
    ledger = svc.last_round_ledger
    validate_solve_ledger(ledger)
    coalesced = svc.metrics()["batch_occupancy"]["cross_request_groups"]
    if not coalesced:
        raise AssertionError("no device batch carried windows from more "
                             "than one request — coalescing broken "
                             f"(groups: {ledger['groups']})")

    # warm repeat: a second wave must compile nothing — 2 cases, so the
    # batch rides the already-compiled bucket width (widths 2..8 all pad
    # to 8; a single window would be the separate single-instance
    # program family)
    fut = svc.submit({k: c for k, c in enumerate(
        synthetic_sensitivity_cases(2, months=months))},
        request_id="warm-repeat")
    fut.result(timeout=600)
    warm_compiles = (svc.last_round_ledger["totals"]["compile_events"])
    if warm_compiles:
        raise AssertionError(
            f"warm repeat round compiled {warm_compiles} program(s) — "
            "the hot-service never-recompiles contract is broken")

    svc.drain()
    m = svc.metrics()
    if m["requests"]["completed"] != n_req + 1:
        raise AssertionError(f"{m['requests']['completed']} of "
                             f"{n_req + 1} requests completed")
    print(json.dumps({
        "smoke": "serve", "ok": True, "requests": n_req,
        "windows": total_windows,
        "coalesced_groups": coalesced,
        "warm_repeat_compile_events": warm_compiles,
        "latency_s": m["latency_s"],
        "batch_occupancy": m["batch_occupancy"],
        "compile_cache": m["compile_cache"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
