"""North-star benchmark: N price scenarios x one year of Battery+PV+DA
dispatch (monthly windows), batched PDHG on the default JAX device.

Prints ONE JSON line:
    {"metric": ..., "value": seconds, "unit": "s", "vs_baseline": ...}

``vs_baseline`` compares against the BASELINE.json target (1000 scenarios
x 8760-h Battery+PV in < 60 s): values > 1.0 beat the target.

The measured number is the steady-state wall time of the batched solves
(all 12 monthly windows x all scenarios), after one warm-up pass that
pays XLA compilation.  Host-side LP assembly happens once per window
structure and is reported separately on stderr.

Env knobs: BENCH_SCENARIOS (default 1000).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_SECONDS = 60.0
BASELINE_SCENARIOS = 1000


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    from dervet_tpu.benchlib import (build_window_lps, scenario_price_batch,
                                     synthetic_case)
    from dervet_tpu.ops.pdhg import CompiledLPSolver, PDHGOptions

    n_scen = int(os.environ.get("BENCH_SCENARIOS", BASELINE_SCENARIOS))
    dev = jax.devices()[0]
    log(f"bench: device={dev.platform}:{dev.device_kind} scenarios={n_scen}")

    t0 = time.time()
    case = synthetic_case()
    scen, groups = build_window_lps(case)
    log(f"bench: assembled {sum(len(v) for v in groups.values())} windows "
        f"({len(groups)} length groups) in {time.time() - t0:.1f}s")

    # one compiled solver per length group; batch = windows-in-group x scenarios
    jobs = []
    for T, lps in sorted(groups.items()):
        solver = CompiledLPSolver(lps[0], PDHGOptions())
        C = np.concatenate([
            scenario_price_batch(lp, n_scen, seed=17) for lp in lps])
        Q = np.repeat(np.stack([lp.q for lp in lps]), n_scen, axis=0)
        L = np.repeat(np.stack([lp.l for lp in lps]), n_scen, axis=0)
        U = np.repeat(np.stack([lp.u for lp in lps]), n_scen, axis=0)
        jobs.append((T, solver, C, Q, L, U))
        log(f"bench: group T={T}: {len(lps)} windows x {n_scen} scenarios "
            f"-> batch {C.shape[0]}, n={lps[0].n}, m={lps[0].m}")

    def run_all():
        results = []
        for T, solver, C, Q, L, U in jobs:
            res = solver.solve(c=C, q=Q, l=L, u=U)
            results.append(res)
        # block on everything
        for res in results:
            res.obj.block_until_ready()
        return results

    t0 = time.time()
    run_all()
    warm = time.time() - t0
    log(f"bench: warm-up (incl. XLA compile): {warm:.1f}s")

    t0 = time.time()
    results = run_all()
    elapsed = time.time() - t0

    n_total = sum(int(np.asarray(r.converged).size) for r in results)
    n_conv = sum(int(np.asarray(r.converged).sum()) for r in results)
    max_it = max(int(np.asarray(r.iters).max()) for r in results)
    log(f"bench: steady-state {elapsed:.2f}s; {n_conv}/{n_total} window-LPs "
        f"converged, worst iters {max_it}")

    # scale the target linearly if running fewer scenarios than the baseline
    baseline = BASELINE_SECONDS * n_scen / BASELINE_SCENARIOS
    print(json.dumps({
        "metric": f"battery_pv_da_year_dispatch_{n_scen}scen_s",
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(baseline / elapsed, 3),
    }))


if __name__ == "__main__":
    main()
