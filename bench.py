"""North-star benchmark: N price scenarios x one year of Battery+PV+DA
dispatch (monthly windows), batched PDHG on the default JAX device.

Prints ONE JSON line:
    {"metric": ..., "value": seconds, "unit": "s", "vs_baseline": ...}

``vs_baseline`` compares against the BASELINE.json target (1000 scenarios
x 8760-h Battery+PV in < 60 s): values > 1.0 beat the target.

The measured number is the steady-state wall time of the batched solves
(all 12 monthly windows x all scenarios), after one warm-up pass that
pays XLA compilation.  Host-side LP assembly happens once per window
structure and is reported separately on stderr.

Env knobs: BENCH_SCENARIOS (default 1000).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_SECONDS = 60.0
BASELINE_SCENARIOS = 1000


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    from dervet_tpu.benchlib import build_window_lps, synthetic_case
    from dervet_tpu.ops.pdhg import CompiledLPSolver, PDHGOptions

    n_scen = int(os.environ.get("BENCH_SCENARIOS", BASELINE_SCENARIOS))
    multi = bool(int(os.environ.get("BENCH_MULTI_DER", "0")))
    dev = jax.devices()[0]
    log(f"bench: device={dev.platform}:{dev.device_kind} scenarios={n_scen}"
        + (" multi-DER microgrid" if multi else ""))

    # BENCH_FUSE=1 pads the 28/30/31-day monthly groups into ONE structure
    # (exact — see build_window_lps): one XLA program, one dispatch per
    # chunk.  Measured on the chip it is a wash (10.3s vs 9.4s steady:
    # ~6% padded-row waste beats the saved dispatches; warm-up identical
    # since the three programs already compile concurrently), so the
    # unfused path stays the default.
    fuse = bool(int(os.environ.get("BENCH_FUSE", "0")))
    t0 = time.time()
    case = synthetic_case(multi_der=multi)
    scen, groups = build_window_lps(case, pad_to_max=fuse)
    log(f"bench: assembled {sum(len(v) for v in groups.values())} windows "
        f"({len(groups)} length groups{', fused' if fuse else ''}) "
        f"in {time.time() - t0:.1f}s")

    # One compiled solver per length group; batch = windows-in-group x
    # scenarios.  Constant problem data (q/l/u per window) is placed on
    # device once at prep, like the LP structure itself; the Monte-Carlo
    # price sweep is drawn ON DEVICE each run from a fresh seed — on a
    # remote chip, shipping a (batch x n) cost matrix over the wire costs
    # more than the entire solve.
    import jax.numpy as jnp

    from dervet_tpu.benchlib import scenario_price_batch_device

    jobs = []
    for T, lps in sorted(groups.items()):
        solver = CompiledLPSolver(lps[0], PDHGOptions())
        c_stack = jnp.asarray(np.stack([lp.c for lp in lps]), jnp.float32)
        Q = jnp.repeat(jnp.asarray(np.stack([lp.q for lp in lps]),
                                   jnp.float32), n_scen, axis=0)
        L = jnp.repeat(jnp.asarray(np.stack([lp.l for lp in lps]),
                                   jnp.float32), n_scen, axis=0)
        U = jnp.repeat(jnp.asarray(np.stack([lp.u for lp in lps]),
                                   jnp.float32), n_scen, axis=0)
        jobs.append((T, solver, c_stack, Q, L, U))
        log(f"bench: group T={T}: {len(lps)} windows x {n_scen} scenarios "
            f"-> batch {Q.shape[0]}, n={lps[0].n}, m={lps[0].m}")

    def run_group(gi, seed):
        T, solver, c_stack, Q, L, U = jobs[gi]
        # (w*n_scen, n) per-scenario costs, one device dispatch
        C = scenario_price_batch_device(c_stack, n_scen, seed + gi)
        res = solver.solve(c=C, q=Q, l=L, u=U)
        return res

    def run_all(seed):
        results = [run_group(gi, seed) for gi in range(len(jobs))]
        # block on everything
        for res in results:
            res.obj.block_until_ready()
        return results

    # warm-up: the three window-length groups compile DIFFERENT XLA
    # programs (batch and m/n shapes differ), so tracing+compiling them
    # serially triples cold-start; one thread per group overlaps the
    # compiles (XLA compiles outside the GIL) while device execution
    # interleaves the (small) first solves (VERDICT r2 #10)
    import concurrent.futures as cf

    t0 = time.time()
    with cf.ThreadPoolExecutor(max_workers=len(jobs)) as pool:
        futs = [pool.submit(run_group, gi, 17) for gi in range(len(jobs))]
        for f in futs:
            f.result().obj.block_until_ready()
    warm = time.time() - t0
    log(f"bench: warm-up (incl. XLA compile, {len(jobs)} groups "
        f"compiled concurrently): {warm:.1f}s")

    # best-of-2: the remote-chip tunnel shows +/-15% run-to-run noise
    # (PERF.md), so a single sample can misreport a steady-state metric by
    # more than any real optimization.  EVERY sampled run must fully
    # converge for its time to count — a fast-but-diverged run is a
    # numerics regression, not a speedup.
    samples = []
    n_total = n_conv = max_it = 0
    iters_all = []
    for seed in (31, 43):
        t0 = time.time()
        results = run_all(seed=seed)
        dt_run = time.time() - t0
        r_total = sum(int(np.asarray(r.converged).size) for r in results)
        r_conv = sum(int(np.asarray(r.converged).sum()) for r in results)
        max_it = max(max_it,
                     max(int(np.asarray(r.iters).max()) for r in results))
        iters_all.append(np.concatenate(
            [np.asarray(r.iters).ravel() for r in results]))
        n_total, n_conv = n_total + r_total, n_conv + r_conv
        if r_conv == r_total:
            samples.append(dt_run)
        else:
            log(f"bench: seed {seed} run excluded from timing — only "
                f"{r_conv}/{r_total} converged")
        del results         # free both runs' solution buffers in HBM
    name = ("microgrid_mc" if multi else "battery_pv_da") \
        + f"_year_dispatch_{n_scen}scen_s"
    if not samples:
        # no fully-converged sample: a numerics regression must fail the
        # scripted run, not masquerade as a (fast) perf number
        log(f"bench: NO fully-converged sample ({n_conv}/{n_total} "
            "window-LPs converged) — metric invalid")
        print(json.dumps({
            "metric": name,
            "value": round(dt_run, 3), "unit": "s", "vs_baseline": 0.0,
        }))
        raise SystemExit(3)
    elapsed = min(samples)
    log(f"bench: steady-state samples {['%.2f' % s for s in samples]} "
        "(reporting min of fully-converged runs)")
    log(f"bench: steady-state {elapsed:.2f}s; {n_conv}/{n_total} window-LPs "
        f"converged across samples, worst iters {max_it}")

    # self-describing solve path (VERDICT r3 #1/#10): which kernel path
    # actually ran, on what, with what iteration profile — so a perf
    # regression is attributable without log archaeology
    from dervet_tpu.ops import pallas_chunk

    group_cfg = []
    for T, solver, c_stack, Q, L, U in jobs:
        group_cfg.append({
            "T": T, "batch": int(Q.shape[0]),
            "n": solver.lp.n, "m": solver.lp.m,
            "pallas": bool(solver.opts.pallas_chunk
                           and pallas_chunk.supports(
                               solver.op, solver.opts.dtype,
                               solver.opts.precision)),
        })
    pallas_used = (not pallas_chunk.RUNTIME_DISABLED
                   and all(g["pallas"] for g in group_cfg))
    it = np.concatenate(iters_all)
    config = {
        "backend": jax.default_backend(),
        "device_kind": dev.device_kind,
        "pallas_blk": pallas_chunk.BLK,
        "compact_chunk_iters": jobs[0][1].opts.compact_chunk_iters,
        "groups": group_cfg,
        "iters": {"p50": int(np.percentile(it, 50)),
                  "p90": int(np.percentile(it, 90)),
                  "p99": int(np.percentile(it, 99)),
                  "max": int(it.max())},
    }
    log(f"bench: pallas={'on' if pallas_used else 'OFF (scan path)'} "
        f"iters p50/p90/p99/max {config['iters']['p50']}/"
        f"{config['iters']['p90']}/{config['iters']['p99']}/"
        f"{config['iters']['max']}")

    # scale the target linearly if running fewer scenarios than the baseline
    baseline = BASELINE_SECONDS * n_scen / BASELINE_SCENARIOS
    print(json.dumps({
        "metric": name,
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(baseline / elapsed, 3),
        "pallas": pallas_used,
        "config": config,
    }))

    if int(os.environ.get("BENCH_REAL_CASE", "0")):
        real_case_leg()


def real_case_leg() -> None:
    """Tie the perf number to validated numerics (VERDICT r2 #9): run a
    REAL reference input (Usecase2 step2 — fixed-size retail + demand-charge
    + User min-SOE dispatch, the golden-validated case whose windows
    genuinely exercise the batched PDHG path) on the jax backend and
    cross-check its NPV against the CPU exact solver in the same process.
    Results go to stderr; the primary metric line stays the contract."""
    from pathlib import Path

    ref = Path("/root/reference/test/test_validation_report_sept1/"
               "Model_params/Usecase2/"
               "Model_Parameters_Template_Usecase3_Planned_ES_Step2.csv")
    if not ref.exists():
        log("bench[real-case]: reference input not available — skipped")
        return
    from dervet_tpu.api import DERVET

    base = Path("/root/reference")
    t0 = time.time()
    inst_j = DERVET(ref, base_path=base).solve(backend="jax").instances[0]
    t_jax = time.time() - t0
    t0 = time.time()
    inst_c = DERVET(ref, base_path=base).solve(backend="cpu").instances[0]
    t_cpu = time.time() - t0
    npv_j = float(inst_j.npv_df["Lifetime Present Value"].iloc[0])
    npv_c = float(inst_c.npv_df["Lifetime Present Value"].iloc[0])
    rel = abs(npv_j - npv_c) / max(1.0, abs(npv_c))
    ok = rel < 1e-2
    log(f"bench[real-case]: UC2-step2 jax {t_jax:.1f}s vs cpu {t_cpu:.1f}s; "
        f"NPV jax {npv_j:,.2f} vs cpu {npv_c:,.2f} (rel err {rel:.2e}; "
        f"gate 1e-2): {'OK' if ok else 'FAIL'}")
    if not ok:
        raise SystemExit(2)     # the gate must fail scripted runs, not log


if __name__ == "__main__":
    main()
