"""North-star benchmark: N price scenarios x one year of Battery+PV+DA
dispatch (monthly windows), batched PDHG on the default JAX device.

Prints ONE JSON line:
    {"metric": ..., "value": seconds, "unit": "s", "vs_baseline": ...}

``vs_baseline`` compares against the BASELINE.json target (1000 scenarios
x 8760-h Battery+PV in < 60 s): values > 1.0 beat the target.

The measured number is the steady-state wall time of the batched solves
(all 12 monthly windows x all scenarios), after one warm-up pass that
pays XLA compilation.  Host-side LP assembly happens once per window
structure and is reported separately on stderr.

Env knobs: BENCH_SCENARIOS (default 1000).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_SECONDS = 60.0
BASELINE_SCENARIOS = 1000


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def check_kernel_gate(ledger, leg: str) -> None:
    """Fail the leg when a chunk-kernel FALLBACK REGRESSION appears in
    its solve ledger: the fused Pallas kernel was eligible and requested
    but a runtime compile failure knocked the dispatch onto the XLA scan
    path (the BENCH_r03 silent-fallback shape — ROADMAP item 4 says it
    must fail a gate, not scroll past as a log line).  Expected scan
    reasons (cpu backend, unsupported shape, single-instance path) are
    not regressions.  Reasons are the machine-stable enums from
    pdhg.KERNEL_FALLBACK_REASONS — the gate matches the
    FALLBACK_RUNTIME_DISABLED enum exactly (plus the legacy
    'runtime_disabled: <detail>' free-form prefix older ledgers
    recorded)."""
    from dervet_tpu.ops.pdhg import FALLBACK_RUNTIME_DISABLED
    kern = (ledger or {}).get("kernel")
    if not kern:
        return
    bad = {r: n for r, n in (kern.get("fallback_reasons") or {}).items()
           if r == FALLBACK_RUNTIME_DISABLED
           or r.startswith(FALLBACK_RUNTIME_DISABLED + ":")}
    if bad:
        log(f"bench[{leg}]: KERNEL FALLBACK REGRESSION — "
            f"{sum(bad.values())} group(s) fell back to the XLA scan "
            f"path after a runtime compile failure: {bad}")
        raise SystemExit(9)


def main() -> None:
    import jax

    from dervet_tpu.benchlib import build_window_lps, synthetic_case
    from dervet_tpu.ops.pdhg import CompiledLPSolver, PDHGOptions

    n_scen = int(os.environ.get("BENCH_SCENARIOS", BASELINE_SCENARIOS))
    multi = bool(int(os.environ.get("BENCH_MULTI_DER", "0")))
    dev = jax.devices()[0]
    log(f"bench: device={dev.platform}:{dev.device_kind} scenarios={n_scen}"
        + (" multi-DER microgrid" if multi else ""))

    # BENCH_FUSE=1 pads the 28/30/31-day monthly groups into ONE structure
    # (exact — see build_window_lps): one XLA program, one dispatch per
    # chunk.  Measured on the chip it is a wash (10.3s vs 9.4s steady:
    # ~6% padded-row waste beats the saved dispatches; warm-up identical
    # since the three programs already compile concurrently), so the
    # unfused path stays the default.
    fuse = bool(int(os.environ.get("BENCH_FUSE", "0")))
    t0 = time.time()
    case = synthetic_case(multi_der=multi)
    scen, groups = build_window_lps(case, pad_to_max=fuse)
    log(f"bench: assembled {sum(len(v) for v in groups.values())} windows "
        f"({len(groups)} length groups{', fused' if fuse else ''}) "
        f"in {time.time() - t0:.1f}s")

    # One compiled solver per length group; batch = windows-in-group x
    # scenarios.  Constant problem data (q/l/u per window) is placed on
    # device once at prep, like the LP structure itself; the Monte-Carlo
    # price sweep is drawn ON DEVICE each run from a fresh seed — on a
    # remote chip, shipping a (batch x n) cost matrix over the wire costs
    # more than the entire solve.
    import jax.numpy as jnp

    from dervet_tpu.benchlib import scenario_price_batch_device

    jobs = []
    for T, lps in sorted(groups.items()):
        solver = CompiledLPSolver(lps[0], PDHGOptions())
        c_stack = jnp.asarray(np.stack([lp.c for lp in lps]), jnp.float32)
        Q = jnp.repeat(jnp.asarray(np.stack([lp.q for lp in lps]),
                                   jnp.float32), n_scen, axis=0)
        L = jnp.repeat(jnp.asarray(np.stack([lp.l for lp in lps]),
                                   jnp.float32), n_scen, axis=0)
        U = jnp.repeat(jnp.asarray(np.stack([lp.u for lp in lps]),
                                   jnp.float32), n_scen, axis=0)
        jobs.append((T, solver, c_stack, Q, L, U))
        log(f"bench: group T={T}: {len(lps)} windows x {n_scen} scenarios "
            f"-> batch {Q.shape[0]}, n={lps[0].n}, m={lps[0].m}")

    def run_group(gi, seed):
        T, solver, c_stack, Q, L, U = jobs[gi]
        # (w*n_scen, n) per-scenario costs, one device dispatch
        C = scenario_price_batch_device(c_stack, n_scen, seed + gi)
        res = solver.solve(c=C, q=Q, l=L, u=U)
        return res

    def run_all(seed):
        results = [run_group(gi, seed) for gi in range(len(jobs))]
        # block on everything
        for res in results:
            res.obj.block_until_ready()
        return results

    # warm-up: the three window-length groups compile DIFFERENT XLA
    # programs (batch and m/n shapes differ), so tracing+compiling them
    # serially triples cold-start; one thread per group overlaps the
    # compiles (XLA compiles outside the GIL) while device execution
    # interleaves the (small) first solves (VERDICT r2 #10)
    import concurrent.futures as cf

    t0 = time.time()
    with cf.ThreadPoolExecutor(max_workers=len(jobs)) as pool:
        futs = [pool.submit(run_group, gi, 17) for gi in range(len(jobs))]
        for f in futs:
            f.result().obj.block_until_ready()
    warm = time.time() - t0
    log(f"bench: warm-up (incl. XLA compile, {len(jobs)} groups "
        f"compiled concurrently): {warm:.1f}s")

    # best-of-2: the remote-chip tunnel shows +/-15% run-to-run noise
    # (PERF.md), so a single sample can misreport a steady-state metric by
    # more than any real optimization.  EVERY sampled run must fully
    # converge for its time to count — a fast-but-diverged run is a
    # numerics regression, not a speedup.
    samples = []
    n_total = n_conv = max_it = 0
    iters_all = []
    group_iters_best = None     # per-group iteration arrays of the best run
    for seed in (31, 43):
        t0 = time.time()
        results = run_all(seed=seed)
        dt_run = time.time() - t0
        r_total = sum(int(np.asarray(r.converged).size) for r in results)
        r_conv = sum(int(np.asarray(r.converged).sum()) for r in results)
        max_it = max(max_it,
                     max(int(np.asarray(r.iters).max()) for r in results))
        run_group_iters = [np.asarray(r.iters).ravel() for r in results]
        iters_all.append(np.concatenate(run_group_iters))
        n_total, n_conv = n_total + r_total, n_conv + r_conv
        if r_conv == r_total:
            if not samples or dt_run < min(samples):
                group_iters_best = run_group_iters
            samples.append(dt_run)
        else:
            log(f"bench: seed {seed} run excluded from timing — only "
                f"{r_conv}/{r_total} converged")
        del results         # free both runs' solution buffers in HBM
    name = ("microgrid_mc" if multi else "battery_pv_da") \
        + f"_year_dispatch_{n_scen}scen_s"
    if not samples:
        # no fully-converged sample: a numerics regression must fail the
        # scripted run, not masquerade as a (fast) perf number
        log(f"bench: NO fully-converged sample ({n_conv}/{n_total} "
            "window-LPs converged) — metric invalid")
        print(json.dumps({
            "metric": name,
            "value": round(dt_run, 3), "unit": "s", "vs_baseline": 0.0,
        }))
        raise SystemExit(3)
    elapsed = min(samples)
    log(f"bench: steady-state samples {['%.2f' % s for s in samples]} "
        "(reporting min of fully-converged runs)")
    log(f"bench: steady-state {elapsed:.2f}s; {n_conv}/{n_total} window-LPs "
        f"converged across samples, worst iters {max_it}")

    # self-describing solve path (VERDICT r3 #1/#10): which kernel path
    # actually ran, on what, with what iteration profile — so a perf
    # regression is attributable without log archaeology
    from dervet_tpu.ops import pallas_chunk

    group_cfg = []
    for T, solver, c_stack, Q, L, U in jobs:
        group_cfg.append({
            "T": T, "batch": int(Q.shape[0]),
            "n": solver.lp.n, "m": solver.lp.m,
            "pallas": bool(solver.opts.pallas_chunk
                           and pallas_chunk.supports(
                               solver.op, solver.opts.dtype,
                               solver.opts.precision,
                               variant=getattr(solver, "variant",
                                               "vanilla"))),
        })
    pallas_used = (not pallas_chunk.RUNTIME_DISABLED
                   and all(g["pallas"] for g in group_cfg))
    it = np.concatenate(iters_all)
    config = {
        "backend": jax.default_backend(),
        "device_kind": dev.device_kind,
        "pallas_blk": pallas_chunk.BLK,
        "compact_chunk_iters": jobs[0][1].opts.compact_chunk_iters,
        "groups": group_cfg,
        "iters": {"p50": int(np.percentile(it, 50)),
                  "p90": int(np.percentile(it, 90)),
                  "p99": int(np.percentile(it, 99)),
                  "max": int(it.max())},
    }
    log(f"bench: pallas={'on' if pallas_used else 'OFF (scan path)'} "
        f"iters p50/p90/p99/max {config['iters']['p50']}/"
        f"{config['iters']['p90']}/{config['iters']['p99']}/"
        f"{config['iters']['max']}")

    # hardware-utilization accounting (VERDICT r5 #4): achieved FLOP/s
    # and modeled HBM traffic for the best fully-converged run, against
    # v5e peaks, so "fast" is measured against the chip, not a wall-clock
    # target.  See hardware_utilization() for the cost model.
    if group_iters_best is not None:
        config["utilization"] = hardware_utilization(
            [j[1] for j in jobs], group_iters_best, elapsed)
        u = config["utilization"]
        log(f"bench: achieved {u['flops_per_s'] / 1e12:.2f} TFLOP/s "
            f"({100 * u['flops_utilization']:.2f}% of bf16 peak), modeled "
            f"HBM {u['hbm_bytes_per_s'] / 1e9:.1f} GB/s "
            f"({100 * u['hbm_utilization']:.1f}% of peak) -> {u['roof']}")

    # secondary legs run BEFORE the primary JSON line is printed so their
    # summaries ride in it; each is fenced so a leg failure still leaves
    # the primary metric on stdout
    legs = {}
    if int(os.environ.get("BENCH_SENS", "1")):
        try:
            legs["sensitivity_fanout"] = sensitivity_leg()
        except Exception as e:          # noqa: BLE001 — leg must not kill bench
            legs["sensitivity_fanout"] = {"error": str(e)[:300]}
    if int(os.environ.get("BENCH_LONG", "1")):
        try:
            legs["long_horizon_5min_year"] = long_horizon_leg()
        except Exception as e:          # noqa: BLE001
            legs["long_horizon_5min_year"] = {"error": str(e)[:300]}
    if int(os.environ.get("BENCH_SERVING", "1")):
        try:
            legs["serving"] = serving_leg()
        except Exception as e:          # noqa: BLE001
            legs["serving"] = {"error": str(e)[:300]}
    if int(os.environ.get("BENCH_ELASTIC", "1")):
        try:
            legs["serving_elastic"] = serving_elastic_leg()
        except Exception as e:          # noqa: BLE001
            legs["serving_elastic"] = {"error": str(e)[:300]}
    if int(os.environ.get("BENCH_WARMSTART", "1")):
        try:
            legs["warm_start"] = warm_start_leg()
        except Exception as e:          # noqa: BLE001
            legs["warm_start"] = {"error": str(e)[:300]}
    if int(os.environ.get("BENCH_SOLVER_CORE", "1")):
        try:
            legs["solver_core"] = solver_core_leg()
        except Exception as e:          # noqa: BLE001
            legs["solver_core"] = {"error": str(e)[:300]}
    if int(os.environ.get("BENCH_KERNEL", "1")):
        try:
            legs["kernel_variant"] = kernel_variant_leg()
        except Exception as e:          # noqa: BLE001
            legs["kernel_variant"] = {"error": str(e)[:300]}
    if int(os.environ.get("BENCH_CHAOS", "1")):
        try:
            legs["serving_chaos"] = serving_chaos_leg()
        except Exception as e:          # noqa: BLE001
            legs["serving_chaos"] = {"error": str(e)[:300]}
    if int(os.environ.get("BENCH_DESIGN", "1")):
        try:
            legs["design"] = design_leg()
        except Exception as e:          # noqa: BLE001
            legs["design"] = {"error": str(e)[:300]}
    if int(os.environ.get("BENCH_FLEET", "1")):
        try:
            legs["serving_fleet"] = serving_fleet_leg()
        except Exception as e:          # noqa: BLE001
            legs["serving_fleet"] = {"error": str(e)[:300]}
    if int(os.environ.get("BENCH_MC", "1")):
        try:
            legs["monte_carlo"] = monte_carlo_leg()
        except Exception as e:          # noqa: BLE001
            legs["monte_carlo"] = {"error": str(e)[:300]}
    if int(os.environ.get("BENCH_PORTFOLIO", "1")):
        try:
            legs["portfolio"] = portfolio_leg()
        except Exception as e:          # noqa: BLE001
            legs["portfolio"] = {"error": str(e)[:300]}
    if int(os.environ.get("BENCH_PORTFOLIO_SCALE", "1")):
        try:
            legs["portfolio_scale"] = portfolio_scale_leg()
        except Exception as e:          # noqa: BLE001
            legs["portfolio_scale"] = {"error": str(e)[:300]}
    if int(os.environ.get("BENCH_REQUEST_CACHE", "1")):
        try:
            legs["request_cache"] = request_cache_leg()
        except Exception as e:          # noqa: BLE001
            legs["request_cache"] = {"error": str(e)[:300]}
    config["legs"] = legs

    # scale the target linearly if running fewer scenarios than the baseline
    baseline = BASELINE_SECONDS * n_scen / BASELINE_SCENARIOS
    print(json.dumps({
        "metric": name,
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(baseline / elapsed, 3),
        "pallas": pallas_used,
        "config": config,
    }))

    if int(os.environ.get("BENCH_REAL_CASE", "0")):
        real_case_leg()


# TPU v5e (lite) public peaks: 197 TFLOP/s bf16 on the MXU, 819 GB/s HBM.
# The solver runs f32 at HIGHEST precision (multi-pass bf16), so bf16 peak
# is the OPTIMISTIC denominator — true attainable is ~1/3 of it; both
# utilizations are reported against the raw peaks for comparability.
V5E_PEAK_FLOPS = 197e12
V5E_PEAK_HBM = 819e9


def _op_nnz_eff(solver) -> int:
    """Effective multiply-add count of one matvec through the solver's
    op: bands nb*m, wide-row pair r*(n+m), ELL residual its padded
    table, dense m*n."""
    from dervet_tpu.ops.pdhg import BandedOp, DenseOp

    n, m = solver.lp.n, solver.lp.m
    op = solver.op
    if isinstance(op, BandedOp):
        nnz_eff = len(op.offsets) * m
        if op.wide_w is not None:
            nnz_eff += int(op.wide_w.shape[0]) * (n + m)
        if op.ell is not None:
            nnz_eff += int(op.ell.data.shape[0] * op.ell.data.shape[1])
            nnz_eff += int(op.ell.dense_blk.shape[0]
                           * op.ell.dense_blk.shape[1])
        return nnz_eff
    if isinstance(op, DenseOp):
        return m * n
    return int(op.data.shape[0] * op.data.shape[1]) \
        + int(op.dense_blk.shape[0] * op.dense_blk.shape[1])


def _utilization_dict(flops: float, hbm: float, elapsed_s: float) -> dict:
    fps = flops / elapsed_s
    bps = hbm / elapsed_s
    fu = fps / V5E_PEAK_FLOPS
    bu = bps / V5E_PEAK_HBM
    # the modeled compute/traffic time at the respective peaks: the
    # fraction of the wall it explains is the honesty check on the label
    modeled_s = max(flops / V5E_PEAK_FLOPS, hbm / V5E_PEAK_HBM)
    explained = modeled_s / elapsed_s
    if fu < 0.10 and bu < 0.10:
        # when BOTH utilizations are ~zero, neither resource is the roof:
        # the path is limited by something the model doesn't count
        # (dispatch overhead, readbacks, VMEM-resident state by design) —
        # labeling the larger of two ~0% numbers "the roof" actively
        # misleads (VERDICT r5 weak #2).  The solve ledger's measured
        # transfer/readback seconds name the real limiter per group.
        roof = ("overhead-bound; modeled traffic explains "
                f"{100.0 * explained:.1f}% of wall")
    else:
        roof = ("hbm-bandwidth-bound" if bu > fu else "compute-bound") \
            + " (modeled)"
    return {
        "flops_per_s": round(fps, 1),
        "hbm_bytes_per_s": round(bps, 1),
        "flops_utilization": round(fu, 6),
        "hbm_utilization": round(bu, 6),
        "peak_flops_bf16": V5E_PEAK_FLOPS,
        "peak_hbm_bytes": V5E_PEAK_HBM,
        "modeled_explained_fraction": round(explained, 4),
        "roof": roof,
    }


def hardware_utilization(solvers, group_iters, elapsed_s) -> dict:
    """Achieved FLOP/s + modeled HBM bytes/s for one timed run.

    FLOP model per instance-iteration (the VERDICT r5 #4 matvec-pair
    formula, extended to the op actually used): 2 matvec directions x
    2 FLOPs per multiply-add over the op's EFFECTIVE nonzeros —
    bands nb*m, wide-row pair r*(n+m), ELL residual its padded table,
    dense m*n — plus ~10(n+m) elementwise update FLOPs.

    HBM model (a LOWER bound, stated as such): with the fused kernel the
    iterate state lives in VMEM, so HBM traffic is (a) one read + one
    write of the (7n+5m)-float block set per instance per CHUNK and
    (b) ~20 (n+m)-float array passes per instance per restart/KKT check
    (every check_every iterations at the then-active batch width).
    Whichever utilization is higher is the roof the path sits under."""
    flops = 0.0
    hbm = 0.0
    for solver, iters in zip(solvers, group_iters):
        n, m = solver.lp.n, solver.lp.m
        nnz_eff = _op_nnz_eff(solver)
        inst_iters = float(np.sum(iters))
        flops += inst_iters * (4.0 * nnz_eff + 10.0 * (n + m))
        chunk = solver.opts.compact_chunk_iters
        check = solver.opts.check_every
        n_chunks = float(np.sum(np.ceil(iters / max(chunk, 1))))
        n_checks = float(np.sum(np.ceil(iters / max(check, 1))))
        hbm += n_chunks * 2.0 * (7 * n + 5 * m) * 4.0
        hbm += n_checks * 20.0 * (n + m) * 4.0
    return _utilization_dict(flops, hbm, elapsed_s)


def sensitivity_leg() -> dict:
    """Product-path TPU proof at sensitivity scale (VERDICT r3 #4): run
    ``DERVET.solve(backend="jax")`` on a REAL reference input fanned out to
    a wide Sensitivity-Parameters list, against the serial exact CPU
    path — proving run_dispatch's cross-case batching (scenario.py) at
    product scale, with per-case NPV parity.  Matches the reference's
    sensitivity fan-out loop (dervet/DERVET.py:75-83), which solves the
    cases one by one."""
    import tempfile
    from pathlib import Path

    src = Path("/root/reference/test/test_storagevet_features/model_params/"
               "000-DA_battery_month.csv")
    if not src.exists():
        return {"skipped": "reference input not available"}
    from dervet_tpu.api import DERVET
    from dervet_tpu.benchlib import widen_sensitivity_csv

    n_cases = int(os.environ.get("BENCH_SENS_CASES", "128"))
    with tempfile.TemporaryDirectory() as td:
        mp = widen_sensitivity_csv(src, Path(td) / "mp_sens.csv", n_cases)
        t0 = time.time()
        res_j = DERVET(mp, base_path="/root/reference").solve(backend="jax")
        t_jax = time.time() - t0
        # warm repeat: the cold number is dominated by one-time XLA
        # compiles (~0.9 s per program over a remote-compile tunnel); a
        # second identical sweep reuses them via the in-process +
        # persistent caches and shows the steady-state product rate
        t0 = time.time()
        res_w = DERVET(mp, base_path="/root/reference").solve(backend="jax")
        t_jax_warm = time.time() - t0
        phases = dict(getattr(res_w, "phase_seconds", {}) or {})
        # the warm run's per-group solve ledger: the 60x per-LP gap
        # decomposed into named line items (iters, dispatches, transfer/
        # readback seconds, compile events, bucket occupancy) — validated
        # well-formed so a schema regression fails the bench, not a
        # downstream reader
        from dervet_tpu.benchlib import validate_solve_ledger
        ledger = getattr(res_w, "solve_ledger", None)
        if ledger is not None:
            validate_solve_ledger(ledger)
            check_kernel_gate(ledger, "sensitivity")
        t0 = time.time()
        res_c = DERVET(mp, base_path="/root/reference").solve(backend="cpu")
        t_cpu = time.time() - t0
    worst = 0.0
    for key in res_c.instances:
        nc = float(res_c.instances[key].npv_df[
            "Lifetime Present Value"].iloc[0])
        nj = float(res_j.instances[key].npv_df[
            "Lifetime Present Value"].iloc[0])
        worst = max(worst, abs(nj - nc) / max(1.0, abs(nc)))
    ok = worst < 1e-2
    log(f"bench[sensitivity]: {n_cases} cases x 12 windows — jax cold "
        f"{t_jax:.1f}s / warm {t_jax_warm:.1f}s (phases {phases}) vs "
        f"serial cpu {t_cpu:.1f}s ({t_cpu / t_jax_warm:.2f}x warm); worst "
        f"per-case NPV rel err {worst:.2e} (gate 1e-2): "
        f"{'OK' if ok else 'FAIL'}")
    cert = (ledger or {}).get("certification")
    if cert and cert.get("enabled"):
        # numerical trust line: the certification + shadow overhead the
        # warm product leg actually paid, and the proof every window
        # carried an accepted float64 certificate (PERF.md "Numerical
        # trust" section cites these numbers)
        from dervet_tpu.ops.certify import validate_certification
        validate_certification(cert)
        cw = cert["windows"]
        n_cert = cert["windows_certified"]
        log("bench[sensitivity]: certification — "
            f"{n_cert} window(s) certified ({cw['certified_loose']} "
            f"loose, {cw['rejected']} rejected) in {cert['cert_s']}s "
            f"({1e3 * cert['cert_s'] / max(n_cert, 1):.2f} ms/window); "
            f"shadow drift max {cert['shadow']['rel_diff_max']:.1e} rel "
            f"over {cert['shadow']['n']} window(s) "
            f"({cert['shadow']['shadow_s']}s)")
    if ledger is not None:
        tot = ledger.get("totals", {})
        log("bench[sensitivity]: solve ledger — "
            f"{tot.get('dispatches')} dispatches / {tot.get('chunks')} "
            f"chunks, {tot.get('compile_events')} compiles, "
            f"{tot.get('h2d_bytes', 0) / 1e6:.1f} MB up in "
            f"{tot.get('h2d_s')}s, sync-wait {tot.get('sync_wait_s')}s, "
            f"result fetch {tot.get('result_fetch_s')}s "
            f"({tot.get('result_bytes', 0) / 1e6:.1f} MB), other "
            f"{tot.get('other_s')}s; accounts for "
            f"{100.0 * (ledger.get('accounted_fraction') or 0):.0f}% of "
            f"dispatch_solve_s ({ledger.get('dispatch_solve_s')}s); "
            f"pipeline={'on' if ledger.get('pipeline') else 'off'} "
            f"depth {ledger.get('max_inflight')}")
    if not ok:
        raise SystemExit(4)
    return {"cases": n_cases, "jax_cold_s": round(t_jax, 2),
            "jax_warm_s": round(t_jax_warm, 2),
            "warm_phases": phases,
            "solve_ledger": ledger,
            "cpu_s": round(t_cpu, 2),
            "speedup_warm": round(t_cpu / t_jax_warm, 2),
            "worst_npv_rel_err": float(f"{worst:.3e}")}


def long_horizon_leg() -> dict:
    """Long-context proof on the chip (VERDICT r3 #5): ONE 5-minute-
    resolution year window (T=105,120 steps, n≈420k variables — the ELL
    path and parallel/timeshard.py's stated design point) solved to HiGHS
    parity, timed.  Matches the reference's 5-min datasets
    (test/datasets/000-004-timeseries_5min*.csv) and SURVEY §5's
    long-context row."""
    from dervet_tpu.benchlib import build_window_lps, synthetic_case
    from dervet_tpu.ops.cpu_ref import solve_lp_cpu
    from dervet_tpu.ops.pdhg import CompiledLPSolver, PDHGOptions

    t0 = time.time()
    case = synthetic_case(dt=1 / 12, n="year")
    _, groups = build_window_lps(case)
    (T, lps), = groups.items()
    lp = lps[0]
    t_asm = time.time() - t0
    # best-of-2 fresh builds, same policy as the main metric's sampling:
    # the dominant precondition cost is a ~4 MB op transfer over the
    # shared tunnel, whose throughput fluctuates >10x run to run
    # (observed 1.8 s vs 12.6 s for the same bytes); a single sample
    # would report tunnel weather, not the code's cost
    t_pre = np.inf
    for _ in range(2):
        t0 = time.time()
        solver = CompiledLPSolver(lp, PDHGOptions(chunk_iters=8192,
                                                  max_iters=200_000))
        t_pre = min(t_pre, time.time() - t0)
    t0 = time.time()
    res = solver.solve()
    t_cold = time.time() - t0
    # steady-state: the cold number carries the one-time XLA compile of
    # the chunk programs; a second solve shows the actual solve rate
    t0 = time.time()
    res = solver.solve()
    t_warm = time.time() - t0
    conv = bool(np.asarray(res.converged))
    t0 = time.time()
    ref = solve_lp_cpu(lp)
    t_cpu = time.time() - t0
    rel = abs(float(res.obj) - ref.obj) / max(1.0, abs(ref.obj))
    ok = conv and rel < 1e-2
    # the honest product-scale comparison is END-TO-END: host precondition
    # + warm chip solve vs HiGHS from the same cold start (VERDICT r4
    # weak #2 — the r4 narrative quoted the chip solve alone)
    e2e = t_pre + t_warm
    log(f"bench[long-horizon]: T={T} n={lp.n} m={lp.m} nnz={lp.K.nnz} — "
        f"assembly {t_asm:.1f}s, precondition {t_pre:.1f}s "
        f"({solver.precondition_breakdown}), chip solve "
        f"cold {t_cold:.1f}s / warm {t_warm:.1f}s ({int(res.iters)} iters, "
        f"converged={conv}); end-to-end {e2e:.1f}s vs HiGHS {t_cpu:.1f}s "
        f"({t_cpu / e2e:.2f}x); obj rel err {rel:.2e} "
        f"(gate 1e-2): {'OK' if ok else 'FAIL'}")
    if not ok:
        raise SystemExit(5)
    # utilization for the UNBATCHED scan path: carries live in HBM, so
    # every iteration re-reads/writes ~12 state/temp vectors of (n+m)
    # plus the band tables — this leg should sit under the HBM roof
    nnz_eff = _op_nnz_eff(solver)
    it = float(res.iters)
    util = _utilization_dict(
        it * (4.0 * nnz_eff + 10.0 * (lp.n + lp.m)),
        it * (12.0 * (lp.n + lp.m) + nnz_eff) * 4.0, t_warm)
    return {"T": int(T), "n": int(lp.n), "m": int(lp.m),
            "chip_solve_cold_s": round(t_cold, 2),
            "chip_solve_warm_s": round(t_warm, 2),
            "precondition_s": round(t_pre, 2),
            "precondition_breakdown": solver.precondition_breakdown,
            "end_to_end_s": round(e2e, 2),
            "highs_s": round(t_cpu, 2),
            "speedup_e2e": round(t_cpu / e2e, 2),
            "iters": int(res.iters),
            "utilization": util,
            "obj_rel_err": float(f"{rel:.3e}")}


def serving_leg() -> dict:
    """Scenario-service proof: a fixed offered load of mixed-size
    requests against a WARM service, vs the cold one-shot ``DERVET.
    solve`` every caller pays today.

    Measured (published under ``legs.serving``): warm single-case
    request latency vs cold solve latency (the acceptance gate: warm
    must win — the service amortizes device warm-up + XLA compiles that
    dominate a cold 1-case run), offered-load latency p50/p99,
    steady-state throughput, batch occupancy (windows per device batch —
    small requests riding coalesced batches), and the compile-cache hit
    rate with the load phase's compile-event count (a hot service's
    steady state is zero).

    The cold number is an IN-PROCESS cold one-shot: fresh solvers, fresh
    compiles — but when this leg runs inside a full ``bench.py`` pass
    the earlier legs have already paid JAX platform init, so it
    understates a truly cold caller.  Run the leg standalone
    (``python -c 'import bench; bench.serving_leg()'``) for a
    cold-process baseline; the PERF.md numbers were measured that
    way."""
    import numpy as _np

    from dervet_tpu.api import DERVET
    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    from dervet_tpu.service import ScenarioService

    months = int(os.environ.get("BENCH_SERVE_MONTHS", "2"))
    n_load = int(os.environ.get("BENCH_SERVE_REQUESTS", "9"))

    def request_cases(n):
        return {i: c for i, c in
                enumerate(synthetic_sensitivity_cases(n, months=months))}

    # cold baseline: fresh one-shot solve of ONE case (device init + XLA
    # compiles + full sweep machinery, nothing amortized)
    t0 = time.time()
    DERVET.from_cases(request_cases(1)).solve(backend="jax")
    t_cold = time.time() - t0

    # telemetry (dervet_tpu/telemetry): reset the process registry so
    # the published snapshot covers THIS leg's serving alone
    from dervet_tpu.telemetry import registry as telemetry_registry
    if telemetry_registry.enabled():
        telemetry_registry.get_registry().reset()

    svc = ScenarioService(backend="jax", max_wait_s=0.05)
    svc.start()
    try:
        t0 = time.time()
        svc.submit(request_cases(1), request_id="warmup").result()
        t_first = time.time() - t0      # the service's own cold start
        warm_lat = []
        for i in range(3):
            t0 = time.time()
            svc.submit(request_cases(1), request_id=f"warm{i}").result()
            warm_lat.append(time.time() - t0)
        t_warm = float(_np.median(warm_lat))

        # offered load: mixed-size requests (1/2/3 cases cycling) pushed
        # concurrently, coalescing through the continuous batcher
        sizes = [1 + (i % 3) for i in range(n_load)]
        compiles_before = svc.metrics()["rounds"]["compile_events"]
        t0 = time.time()
        futs = [svc.submit(request_cases(sz), request_id=f"load{i}")
                for i, sz in enumerate(sizes)]
        results = [f.result() for f in futs]
        t_load = time.time() - t0
        m = svc.metrics()
        check_kernel_gate(svc.last_round_ledger, "serving")
        telem_snap = (telemetry_registry.get_registry().snapshot()
                      if telemetry_registry.enabled() else None)
    finally:
        svc.close()

    lat = sorted(r.request_latency_s for r in results)
    p50 = float(_np.percentile(lat, 50))
    p99 = float(_np.percentile(lat, 99))
    total_cases = sum(sizes)
    total_windows = sum(sl["totals"]["windows"] for sl in
                        (r.solve_ledger for r in results) if sl)
    load_compiles = m["rounds"]["compile_events"] - compiles_before
    occupancy = m["batch_occupancy"]["mean_windows_per_device_batch"]
    hit_rate = m["compile_cache"]["hit_rate"]
    ok = t_warm < t_cold
    log(f"bench[serving]: warm single-case {t_warm * 1e3:.0f}ms vs cold "
        f"DERVET.solve {t_cold:.2f}s ({t_cold / t_warm:.1f}x; service "
        f"first-request {t_first:.2f}s); offered load {n_load} requests "
        f"({total_cases} cases, {total_windows} windows) in {t_load:.2f}s "
        f"-> {total_cases / t_load:.2f} cases/s, latency p50/p99 "
        f"{p50 * 1e3:.0f}/{p99 * 1e3:.0f}ms; occupancy "
        f"{occupancy:.1f} windows/device batch, compile-cache hit rate "
        f"{hit_rate}, load-phase compiles {load_compiles}; "
        f"warm-beats-cold gate: {'OK' if ok else 'FAIL'}")
    if not ok:
        raise SystemExit(6)
    # registry snapshot published + schema-validated alongside the solve
    # ledger (the telemetry plane's bench surface); the histogram p50 is
    # cross-checked against the directly-measured latencies — the merge
    # math must agree with reality within the log-bucket resolution
    telemetry = None
    if telem_snap is not None:
        from dervet_tpu.benchlib import validate_telemetry_section
        from dervet_tpu.telemetry.registry import quantile_from_buckets
        validate_telemetry_section(telem_snap)
        hist = telem_snap["histograms"].get(
            "dervet_request_latency_seconds")
        hist_p50 = (quantile_from_buckets(hist, 0.5) if hist else None)
        if hist_p50 is not None and p50 > 0 and \
                not (p50 / 2.5 <= hist_p50 <= p50 * 2.5):
            raise SystemExit(
                f"bench[serving]: telemetry histogram p50 {hist_p50:.4f}s"
                f" disagrees with measured p50 {p50:.4f}s beyond the "
                "log-bucket resolution")
        telemetry = {**telem_snap,
                     "latency_hist_p50_s": (round(hist_p50, 4)
                                            if hist_p50 else None)}
    return {
        "requests": n_load,
        "telemetry": telemetry,
        "cases": total_cases,
        "cold_solve_single_case_s": round(t_cold, 3),
        "service_first_request_s": round(t_first, 3),
        "warm_single_case_s": round(t_warm, 4),
        "warm_vs_cold_speedup": round(t_cold / t_warm, 1),
        "offered_load_s": round(t_load, 3),
        "throughput_cases_per_s": round(total_cases / t_load, 2),
        "latency_p50_s": round(p50, 4),
        "latency_p99_s": round(p99, 4),
        "batch_occupancy_windows": occupancy,
        "compile_cache_hit_rate": hit_rate,
        "load_phase_compile_events": int(load_compiles),
        "queue": {k: m["queue"][k] for k in
                  ("admitted", "rejected_full", "rejected_overload",
                   "expired")},
    }


def serving_elastic_leg() -> dict:
    """Elastic mesh-serving proof (parallel/elastic.py): the SAME mixed
    workload served three ways — single-device scheduler
    (``DERVET_TPU_ELASTIC_DEVICES=1``), the serial global scheduler
    (``DERVET_TPU_ELASTIC=0``: one shard_map stream, devices take turns),
    and the elastic mesh-wide scheduler (per-device in-flight rounds +
    work stealing).

    The workload is N requests whose window lengths differ (the ``n``
    optimization-hours knob), so one round fans out to more structure
    groups than devices and placement/stealing has something to do.
    Each pass runs against a FRESH service with the warm-start memory
    disabled (substitution would zero the device work and measure
    nothing); the timed pass is the warm second round, after one
    untimed round pays the XLA compiles.

    Gates: elastic results BYTE-IDENTICAL to the single-device
    schedule's (always — placement, mesh size, and stealing may change
    where windows solve, never what they solve to; the legacy sharded
    scheduler's bits vary with per-device batch width, so against it
    the gate is certification-level tolerance); on a real >= 8-
    accelerator mesh (not virtual CPU host devices, which share
    physical cores and cannot exhibit real scaling): aggregate
    throughput >= 4x the single-device scheduler and mean per-device
    occupancy >= 0.70; kernel-fallback regression fails the gate
    everywhere."""
    import numpy as _np

    import jax

    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    from dervet_tpu.service import ScenarioService
    from dervet_tpu.telemetry import registry as telemetry_registry

    # the published snapshot must cover THIS leg alone (earlier legs in
    # the same bench process accumulate into the process registry)
    if telemetry_registry.enabled():
        telemetry_registry.get_registry().reset()

    n_dev = len(jax.devices())
    platform = jax.devices()[0].platform
    months = int(os.environ.get("BENCH_ELASTIC_MONTHS", "1"))
    cases_per = int(os.environ.get("BENCH_ELASTIC_CASES", "2"))
    n_lengths = int(os.environ.get("BENCH_ELASTIC_LENGTHS",
                                   str(max(8, min(16, 2 * n_dev)))))
    # distinct window lengths -> distinct structure groups (+ tail
    # remainders); horizon is months x ~744 h
    lengths = [72 + 24 * i for i in range(n_lengths)]

    def workload():
        return {f"el{i}": {j: c for j, c in enumerate(
                    synthetic_sensitivity_cases(cases_per, n=n,
                                                months=months))}
                for i, n in enumerate(lengths)}

    def run_pass(tag, elastic_env, devices_env=None):
        prev = {k: os.environ.get(k) for k in
                ("DERVET_TPU_ELASTIC", "DERVET_TPU_ELASTIC_DEVICES",
                 "DERVET_TPU_WARMSTART")}
        os.environ["DERVET_TPU_ELASTIC"] = elastic_env
        if devices_env is None:
            os.environ.pop("DERVET_TPU_ELASTIC_DEVICES", None)
        else:
            os.environ["DERVET_TPU_ELASTIC_DEVICES"] = devices_env
        os.environ["DERVET_TPU_WARMSTART"] = "0"
        try:
            # no batcher thread: each wave is submitted and then driven
            # through ONE deterministic run_once round, so the round
            # ledger the gates read covers the whole timed pass (a
            # background batcher could split a wave across rounds and
            # leave last_round_ledger describing only the tail)
            svc = ScenarioService(backend="jax", max_wait_s=0.0,
                                  max_batch_requests=64)
            try:
                # round 1 (untimed): pays the XLA compiles
                futs = {r: svc.submit(c, request_id=f"warm.{r}")
                        for r, c in workload().items()}
                svc.run_once()
                for f in futs.values():
                    f.result()
                # round 2 (timed): the steady-state serving rate
                futs = {r: svc.submit(c, request_id=r)
                        for r, c in workload().items()}
                t0 = time.time()
                svc.run_once()
                results = {r: f.result() for r, f in futs.items()}
                wall = time.time() - t0
                led = svc.last_round_ledger
                check_kernel_gate(led, "serving_elastic")
                n_windows = sum((r.solve_ledger or {}).get(
                    "totals", {}).get("windows", 0)
                    for r in results.values())
                log(f"bench[serving_elastic]: {tag} — {len(results)} "
                    f"requests / {n_windows} windows in {wall:.2f}s "
                    f"({n_windows / wall:.1f} windows/s)")
                return {"wall_s": wall, "windows": n_windows,
                        "results": results, "ledger": led}
            finally:
                svc.close()
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    single = run_pass("single-device", "1", devices_env="1")
    serial = run_pass("serial global scheduler", "0")
    elastic = run_pass("elastic mesh scheduler", "1")

    # byte identity vs the single-device schedule: the elastic
    # scheduler must change WHERE windows solve, never what they solve
    # to.  The serial sharded scheduler is compared at certification
    # tolerance (its per-device batch width changes the dense-op XLA
    # reduction order, so its bits depend on the mesh size — elastic's
    # do not).
    identical = True
    serial_close = True
    for rid, re_ in elastic["results"].items():
        ru, rs = single["results"][rid], serial["results"][rid]
        for key in re_.instances:
            ie, iu, is_ = (re_.instances[key], ru.instances[key],
                           rs.instances[key])
            if ie.scenario.objective_values != iu.scenario.objective_values:
                identical = False
                log(f"bench[serving_elastic]: objective mismatch vs "
                    f"single-device {rid}/{key}")
            for name in ie.scenario._solution:
                if not _np.array_equal(ie.scenario._solution[name],
                                       iu.scenario._solution[name]):
                    identical = False
                    log(f"bench[serving_elastic]: solution mismatch vs "
                        f"single-device {rid}/{key}/{name}")
            for w, oe in ie.scenario.objective_values.items():
                os_ = is_.scenario.objective_values[w]["Total Objective"]
                if abs(oe["Total Objective"] - os_) > \
                        1e-5 * max(1.0, abs(os_)):
                    serial_close = False
                    log(f"bench[serving_elastic]: serial-scheduler "
                        f"objective drift {rid}/{key}/{w}")

    el = (elastic["ledger"] or {}).get("elastic") or {}
    occ = [d["occupancy"] for d in (el.get("devices") or {}).values()
           if d["groups"]]
    mean_occ = float(_np.mean(occ)) if occ else 0.0
    speedup_single = single["wall_s"] / elastic["wall_s"]
    speedup_serial = serial["wall_s"] / elastic["wall_s"]
    real_mesh = platform != "cpu" and n_dev >= 8
    gates = {"byte_identity_vs_single_device": identical,
             "serial_scheduler_within_tolerance": serial_close}
    if real_mesh:
        gates["throughput_4x_vs_single_device"] = speedup_single >= 4.0
        gates["mean_occupancy_ge_70"] = mean_occ >= 0.70
    ok = all(gates.values())
    log(f"bench[serving_elastic]: {n_dev}x {platform} — elastic "
        f"{elastic['wall_s']:.2f}s vs serial {serial['wall_s']:.2f}s "
        f"({speedup_serial:.2f}x) vs single-device "
        f"{single['wall_s']:.2f}s ({speedup_single:.2f}x); "
        f"devices with groups {el.get('devices_with_groups')}/{n_dev}, "
        f"steals {el.get('n_steals')}, mean occupancy {mean_occ:.2f} "
        f"(min {min(occ) if occ else 0:.2f}); byte-identity "
        f"{'OK' if identical else 'FAIL'}; gates "
        f"{'OK' if ok else 'FAIL'}"
        + ("" if real_mesh else
           " (4x/occupancy gates skipped: virtual/CPU mesh shares "
           "physical cores)"))
    if not ok:
        raise SystemExit(8)
    return {
        "n_devices": n_dev,
        "platform": platform,
        "requests": len(lengths),
        "windows": elastic["windows"],
        "single_device_wall_s": round(single["wall_s"], 3),
        "serial_wall_s": round(serial["wall_s"], 3),
        "elastic_wall_s": round(elastic["wall_s"], 3),
        "speedup_vs_single_device": round(speedup_single, 2),
        "speedup_vs_serial": round(speedup_serial, 2),
        "throughput_windows_per_s": round(
            elastic["windows"] / elastic["wall_s"], 2),
        "devices_with_groups": el.get("devices_with_groups"),
        "steals": el.get("n_steals"),
        "occupancy_mean": round(mean_occ, 3),
        "occupancy_min": round(min(occ), 3) if occ else None,
        "per_device": el.get("devices"),
        "byte_identical_to_single_device": identical,
        "serial_scheduler_within_tolerance": serial_close,
        "gates": gates,
        "gated_on_real_mesh": real_mesh,
        # registry snapshot (accumulated over the three passes),
        # schema-validated like the solve ledger
        "telemetry": _telemetry_section(),
    }


def _telemetry_section():
    """The process metrics-registry snapshot, schema-validated, for a
    serving leg's published artifact (None under the kill switch)."""
    from dervet_tpu.benchlib import validate_telemetry_section
    from dervet_tpu.telemetry import registry as telemetry_registry
    if not telemetry_registry.enabled():
        return None
    return validate_telemetry_section(
        telemetry_registry.get_registry().snapshot())


def solver_core_leg() -> dict:
    """Solver-core proof (ops/pdhg.py variants + ops/seedpredict.py):
    the iteration COUNT is the product-path ceiling (BENCH_r05: iters
    p50 1664 at 0.26% FLOPs utilization), and the step variants + the
    learned cold-start predictor attack it directly.

    Four cold passes over one sensitivity-fanout batch (a monthly
    dispatch window structure x BENCH_CORE_BATCH perturbed-price
    instances): vanilla, reflected, halpern, and halpern seeded by the
    learned predictor (trained on a DISJOINT batch of the same
    structure — the structure-repeat cold shape).  Published under
    ``legs.solver_core`` with iters p50/p99 and wall per pass, plus the
    chunk-kernel selection per pass (the kernel gate fails the leg on a
    runtime_disabled fallback exactly like the dispatch legs).

    Gates: the default variant alone >= 30% median cold-iteration
    reduction vs vanilla; halpern+predicted >= 2x vs vanilla cold; all
    passes 100% converged."""
    import numpy as _np

    from dervet_tpu.benchlib import build_window_lps, synthetic_case
    from dervet_tpu.ops import warmstart
    from dervet_tpu.ops.pdhg import (CompiledLPSolver, PDHGOptions,
                                     kernel_selection)

    batch = int(os.environ.get("BENCH_CORE_BATCH", "16"))
    case = synthetic_case()
    _, groups = build_window_lps(case)
    lp0 = sorted(groups.items())[0][1][0]
    rng = _np.random.default_rng(7)

    # structure-repeat cold traffic: per-instance price-LEVEL shift
    # (±15%) over a stable hourly shape plus idiosyncratic per-hour
    # noise.  At resubmission-grade noise (0.3% — well past the float16
    # quant digest, so these are genuinely cold: no near grade fires)
    # the systematic component dominates and a learned seed recovers
    # most of the iterate; at 1% per-hour noise the optimal dispatch
    # basis itself shifts instance-to-instance, which NO seed-based
    # method can predict — that row is reported (noise_sensitivity) but
    # not gated.
    def fanout(n, noise=0.003):
        s = rng.uniform(0.85, 1.15, n)
        return _np.stack([lp0.c * s[i] * (1 + noise * rng.standard_normal(
            lp0.c.shape)) for i in range(n)])

    C = fanout(batch)

    def run(opts, x0=None, y0=None):
        solver = CompiledLPSolver(lp0, opts)
        t0 = time.time()
        res = solver.solve(c=C, x0=x0, y0=y0)
        it = _np.asarray(res.iters)
        conv = int(_np.asarray(res.converged).sum())
        kern, kern_why, kern_detail = kernel_selection(solver, batched=True)
        if conv != batch:
            raise AssertionError(
                f"solver_core: {conv}/{batch} converged under "
                f"{opts.variant}")
        return {"iters_p50": int(_np.percentile(it, 50)),
                "iters_p99": int(_np.percentile(it, 99)),
                "wall_s": round(time.time() - t0, 2),
                "restarts": int(_np.asarray(res.restarts).sum()),
                "restart_scheme": solver.restart_scheme,
                "kernel": kern,
                **({"kernel_fallback": kern_why} if kern_why else {}),
                **({"kernel_fallback_detail": kern_detail}
                   if kern_detail else {})}

    passes = {
        "vanilla": run(PDHGOptions(variant="vanilla")),
        "reflected": run(PDHGOptions(variant="reflected")),
        "halpern": run(PDHGOptions(variant="halpern")),
    }

    # halpern+predicted: train the memory/predictor on a disjoint batch
    # of the same structure, then serve predictions for the bench batch
    train_opts = PDHGOptions(variant="halpern")
    trainer = CompiledLPSolver(lp0, train_opts)
    mem = warmstart.SolutionMemory(max_entries=64)
    tag = warmstart.opts_tag(train_opts)
    Ct = fanout(8)
    rt = trainer.solve(c=Ct)
    import copy as _copy

    def _mk_lp(c_row):
        lpi = _copy.copy(lp0)
        lpi.c = c_row
        return lpi

    for i in range(Ct.shape[0]):
        mem.store("bench-core", _mk_lp(Ct[i]), tag, _np.asarray(rt.x)[i],
                  _np.asarray(rt.y)[i], float(_np.asarray(rt.obj)[i]))
    plans = warmstart.plan_group(mem, "bench-core",
                                 [_mk_lp(C[i]) for i in range(batch)],
                                 train_opts, list(range(batch)))
    n_pred = sum(1 for p in plans if p.kind == "predicted")
    X0 = _np.stack([p.entry.x if p.entry is not None
                    else _np.zeros(lp0.n) for p in plans])
    Y0 = _np.stack([p.entry.y if p.entry is not None
                    else _np.zeros(lp0.m) for p in plans])
    passes["halpern_predicted"] = {**run(train_opts, x0=X0, y0=Y0),
                                   "predicted": n_pred}

    # ungated sensitivity row: the same predicted-seed recipe against a
    # 1% per-hour-noise fanout, quantifying how the win degrades as the
    # idiosyncratic (basis-shifting) component grows
    Cn = fanout(batch, noise=0.01)
    plans_n = warmstart.plan_group(
        mem, "bench-core", [_mk_lp(Cn[i]) for i in range(batch)],
        train_opts, list(range(batch)))
    Xn = _np.stack([p.entry.x if p.entry is not None
                    else _np.zeros(lp0.n) for p in plans_n])
    Yn = _np.stack([p.entry.y if p.entry is not None
                    else _np.zeros(lp0.m) for p in plans_n])
    noise_solver = CompiledLPSolver(lp0, train_opts)
    res_n = noise_solver.solve(c=Cn, x0=Xn, y0=Yn)
    res_v = CompiledLPSolver(
        lp0, PDHGOptions(variant="vanilla")).solve(c=Cn)
    noise_sens = {
        "noise": 0.01,
        "iters_p50_vanilla_cold": int(_np.percentile(
            _np.asarray(res_v.iters), 50)),
        "iters_p50_halpern_predicted": int(_np.percentile(
            _np.asarray(res_n.iters), 50)),
    }

    # the kernel gate, wired exactly like the dispatch legs: a
    # runtime_disabled fallback on any pass is a regression
    from collections import Counter
    reasons = Counter(p["kernel_fallback"] for p in passes.values()
                      if p.get("kernel_fallback"))
    check_kernel_gate({"kernel": {"fallback_reasons": dict(reasons)}},
                      "solver_core")

    van = passes["vanilla"]["iters_p50"]
    variant_red = 1.0 - passes["reflected"]["iters_p50"] / van
    pred_speedup = van / max(passes["halpern_predicted"]["iters_p50"], 1)
    ok = variant_red >= 0.30 and pred_speedup >= 2.0 and n_pred == batch
    log(f"bench[solver_core]: iters p50 vanilla {van} -> reflected "
        f"{passes['reflected']['iters_p50']} "
        f"({100 * variant_red:.0f}% reduction) -> halpern "
        f"{passes['halpern']['iters_p50']} -> halpern+predicted "
        f"{passes['halpern_predicted']['iters_p50']} "
        f"({pred_speedup:.1f}x, {n_pred}/{batch} predicted); "
        f"gate: {'OK' if ok else 'FAIL'}")
    if not ok:
        raise SystemExit(10)    # 8/9 are the warm-start/kernel codes
    return {
        "batch": batch, "m": lp0.m, "n": lp0.n,
        "passes": passes,
        "variant_reduction": round(variant_red, 4),
        "predicted_speedup": round(pred_speedup, 2),
        "predicted_fraction": round(n_pred / batch, 3),
        "noise_sensitivity": noise_sens,
    }


def kernel_variant_leg() -> dict:
    """Variant x kernel A/B (ROADMAP item 1a — the PR-11 remainder):
    the fused Pallas chunk is VARIANT-NATIVE now, so the 34-39%
    iteration cut (reflected) and the kernel's ~10-12% HBM cut finally
    COMPOUND.  On a real TPU this leg runs a back-to-back A/B at the
    batch-700 bench shape per variant — kernel vs scan, same process —
    and GATES the reflected kernel >= 8% faster than reflected-scan.
    On any other backend the leg is STRUCTURAL ONLY (``gated_on_real_
    mesh`` false): a small LP under ``DERVET_TPU_PALLAS_INTERPRET=1``
    proves the real kernel executes for all three variants, is chosen by
    kernel_selection, and matches the scan path (vanilla bitwise,
    variants to certification tolerance) — no timing claims from a CPU
    interpreting the kernel."""
    import jax
    import numpy as _np

    from dervet_tpu.ops import pallas_chunk
    from dervet_tpu.ops.pdhg import (CompiledLPSolver, KERNEL_PALLAS,
                                     PDHGOptions, kernel_selection)

    real_tpu = jax.default_backend() == "tpu"
    variants = ("vanilla", "reflected", "halpern")

    if not real_tpu:
        # structural pass: tiny battery-like LP, interpret-mode kernel
        # vs scan, per variant.  Shapes stay small on purpose — the
        # interpret path executes the kernel body as plain jax ops, so
        # bench shapes would burn CI minutes proving nothing extra.
        from dervet_tpu.ops.lp import LPBuilder
        import scipy.sparse as _sp

        T = 48
        b = LPBuilder()
        ch = b.var("ch", T, 0, 10)
        dis = b.var("dis", T, 0, 10)
        e = b.var("e", T, 0, 40)
        rng = _np.random.default_rng(3)
        price = rng.uniform(10, 50, T)
        b.add_cost(ch, price)
        b.add_cost(dis, -price)
        D = _sp.diags([_np.ones(T), -_np.ones(T - 1)], [0, -1])
        b.add_rows("soe", [(e, D), (ch, -0.9 * _sp.eye(T)),
                           (dis, (1 / 0.9) * _sp.eye(T))], "eq",
                   _np.r_[20.0, _np.zeros(T - 1)])
        b.add_rows("req", [(dis, _np.ones((1, T)))], "ge", 5.0)
        lp = b.build()
        B = 5                       # non-multiple of BLK: padding rows
        C = _np.stack([lp.c * (1 + 0.01 * i) for i in range(B)])
        rows = {}
        prev = os.environ.get(pallas_chunk.INTERPRET_ENV)
        try:
            os.environ[pallas_chunk.INTERPRET_ENV] = "1"
            for v in variants:
                sk = CompiledLPSolver(lp, PDHGOptions(variant=v))
                kern, why, _ = kernel_selection(sk, batched=True)
                if kern != KERNEL_PALLAS:
                    raise AssertionError(
                        f"kernel_variant[{v}]: interpret mode did not "
                        f"select the kernel ({kern}: {why})")
                rk = sk.solve(c=C)
                os.environ[pallas_chunk.INTERPRET_ENV] = "0"
                rs = CompiledLPSolver(lp, PDHGOptions(variant=v)).solve(c=C)
                os.environ[pallas_chunk.INTERPRET_ENV] = "1"
                dx = float(_np.abs(_np.asarray(rk.x)
                                   - _np.asarray(rs.x)).max())
                rows[v] = {"kernel": kern, "max_abs_dx_vs_scan": dx,
                           "bitwise": bool(_np.array_equal(
                               _np.asarray(rk.x), _np.asarray(rs.x))),
                           "converged": int(_np.asarray(
                               rk.converged).sum()) == B}
                if not rows[v]["converged"] or dx > 1e-4:
                    raise AssertionError(
                        f"kernel_variant[{v}]: interpret kernel diverged "
                        f"from scan (max|dx| {dx})")
        finally:
            if prev is None:
                os.environ.pop(pallas_chunk.INTERPRET_ENV, None)
            else:
                os.environ[pallas_chunk.INTERPRET_ENV] = prev
        log("bench[kernel_variant]: structural interpret-mode pass — "
            + ", ".join(f"{v}: kernel, max|dx| "
                        f"{rows[v]['max_abs_dx_vs_scan']:.1e}"
                        for v in variants)
            + " (>=8% timing gate skipped: not a TPU)")
        return {"structural_only": True, "variants": rows,
                "gated_on_real_mesh": False}

    # real chip: back-to-back kernel-vs-scan A/B per variant at the
    # batch-700 bench shape (the PERF.md "Fused Pallas iteration chunk"
    # measurement, now per variant)
    from dervet_tpu.benchlib import build_window_lps, synthetic_case

    batch = int(os.environ.get("BENCH_KERNEL_BATCH", "700"))
    case = synthetic_case()
    _, groups = build_window_lps(case)
    lp0 = sorted(groups.items())[0][1][0]
    rng = _np.random.default_rng(11)
    C = _np.stack([lp0.c * (1 + 0.02 * rng.standard_normal(lp0.c.shape))
                   for _ in range(batch)])

    def timed(opts):
        solver = CompiledLPSolver(lp0, opts)
        kern, why, _ = kernel_selection(solver, batched=True)
        walls = []
        for _ in range(2):          # warm-up + steady state
            t0 = time.time()
            res = solver.solve(c=C)
            jax.block_until_ready(res.x)
            walls.append(time.time() - t0)
        it = _np.asarray(res.iters)
        return {"kernel": kern,
                **({"kernel_fallback": why} if why else {}),
                "wall_s": round(min(walls), 3),
                "iters_p50": int(_np.percentile(it, 50)),
                "converged": int(_np.asarray(res.converged).sum())}

    rows = {}
    for v in variants:
        rows[v] = {
            "pallas": timed(PDHGOptions(variant=v)),
            "scan": timed(PDHGOptions(variant=v, pallas_chunk=False)),
        }
    refl = rows["reflected"]
    speedup = refl["scan"]["wall_s"] / max(refl["pallas"]["wall_s"], 1e-9)
    ok = (speedup >= 1.08
          and refl["pallas"]["kernel"] == KERNEL_PALLAS
          and all(rows[v]["pallas"]["converged"] == batch for v in variants))
    log(f"bench[kernel_variant]: batch {batch} reflected kernel "
        f"{refl['pallas']['wall_s']:.2f}s vs scan "
        f"{refl['scan']['wall_s']:.2f}s ({speedup:.2f}x); gate "
        f"{'OK' if ok else 'FAIL'}")
    if not ok:
        raise SystemExit(9)
    return {"batch": batch, "m": lp0.m, "n": lp0.n, "variants": rows,
            "reflected_kernel_speedup": round(speedup, 3),
            "gated_on_real_mesh": True}


def warm_start_leg() -> dict:
    """Warm-start proof (ops/warmstart.py): iteration count is the
    hot-path cost (BENCH_r05: iters p50 1664 at 0.26% FLOPs
    utilization), and the solution memory attacks it directly.

    Three passes against one service (published under
    ``legs.warm_start``): a COLD request (the baseline), the IDENTICAL
    request again (exact-match path — the stored solutions re-verify in
    float64 and ship verbatim, so the seeded iteration count is 0 and
    results are byte-identical), and a NEAR request (same window
    structure, different prices — genuine iterate seeding through
    ``init_state(x0=, y0=)``).  Gates: >= 30% median iteration
    reduction on the repeat pass, zero compile events on it, and a
    seeded-window fraction of 1.0 on both warm passes."""
    import numpy as _np

    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    from dervet_tpu.service import ScenarioService

    months = int(os.environ.get("BENCH_WARM_MONTHS", "2"))
    n_cases = int(os.environ.get("BENCH_WARM_CASES", "2"))
    family = synthetic_sensitivity_cases(n_cases, months=months)
    # the NEAR pass models the rolling-resubmission serving shape: the
    # same request with the battery rating nudged 1% — same structure,
    # nearby data, a genuine iterate seed (no substitution possible)
    near_family = synthetic_sensitivity_cases(n_cases, months=months)
    for c in near_family:
        for tag, _, keys in c.ders:
            if tag == "Battery":
                keys["ene_max_rated"] *= 1.01

    def req(fam):
        return {i: c for i, c in enumerate(fam)}

    svc = ScenarioService(backend="jax", max_wait_s=0.05)
    svc.start()
    try:
        def pass_(cases, rid):
            t0 = time.time()
            res = svc.submit(cases, request_id=rid).result()
            dt = time.time() - t0
            led = svc.last_round_ledger
            return res, led, dt

        _, cold_led, t_cold = pass_(req(family), "ws-cold")
        _, warm_led, t_warm = pass_(req(family), "ws-repeat")
        _, near_led, t_near = pass_(req(near_family), "ws-near")
        mem = svc.metrics()["warm_start"]
    finally:
        svc.close()

    def stats(led):
        w = led.get("warm_start") or {}
        return {
            "iters_p50": led["iters"]["p50"] if "iters" in led else None,
            "iters_p99": led["iters"]["p99"] if "iters" in led else None,
            "seeded": w.get("seeded", 0),
            "substituted": w.get("substituted", 0),
            "seeded_fraction": w.get("seeded_fraction", 0.0),
            "iters_p50_seeded": w.get("iters_p50_seeded"),
            "iters_saved": w.get("iters_saved"),
            "compile_events": int(led["totals"]["compile_events"]),
        }

    cold_s, warm_s, near_s = (stats(x) for x in
                              (cold_led, warm_led, near_led))
    cold_p50 = (cold_led.get("warm_start") or {}).get("iters_p50_cold") \
        or cold_s["iters_p50"]
    repeat_p50 = warm_s["iters_p50_seeded"]
    # a warm pass that seeded NOTHING is a gate failure, not a leg
    # error: None must fail `ok`, never raise past the gate into the
    # leg-level except arm (which would record an 'error' and exit 0)
    reduction = (1.0 - repeat_p50 / cold_p50) \
        if cold_p50 and repeat_p50 is not None else 0.0
    near_p50 = near_s["iters_p50_seeded"]
    near_reduction = ((1.0 - near_p50 / cold_p50)
                      if cold_p50 and near_p50 is not None else None)
    ok = (repeat_p50 is not None and reduction >= 0.30
          and warm_s["compile_events"] == 0
          and warm_s["seeded_fraction"] == 1.0
          and near_s["seeded_fraction"] == 1.0)
    log(f"bench[warm_start]: iters p50 cold {cold_p50} -> repeat "
        f"{repeat_p50} ({100 * reduction:.0f}% reduction, "
        f"{warm_s['substituted']} substituted, "
        f"{warm_s['compile_events']} compiles) -> near {near_p50} "
        f"({'' if near_reduction is None else f'{100 * near_reduction:.0f}% reduction'}); "
        f"request wall cold {t_cold:.2f}s / repeat {t_warm:.2f}s / near "
        f"{t_near:.2f}s; gate: {'OK' if ok else 'FAIL'}")
    if not ok:
        raise SystemExit(8)     # 7 is the design leg's gate code
    return {
        "months": months, "cases": n_cases,
        "iters_p50_cold": int(cold_p50),
        "iters_p99_cold": cold_s["iters_p99"],
        "repeat": warm_s, "near": near_s,
        "repeat_reduction": round(reduction, 4),
        "near_reduction": (round(near_reduction, 4)
                           if near_reduction is not None else None),
        "request_s": {"cold": round(t_cold, 3),
                      "repeat": round(t_warm, 3),
                      "near": round(t_near, 3)},
        "serving_latency_delta_s": round(t_cold - t_warm, 3),
        "memory": mem,
    }


def serving_chaos_leg() -> dict:
    """Self-healing proof: the seeded chaos/soak drill
    (``scripts/chaos_soak.py``) against a live service — overload bursts
    (load-shed degraded answers), watchdog hangs, corrupt solutions,
    device losses, poison requests — published under
    ``legs.serving_chaos``.  Gates: zero lost requests, zero uncertified
    answers stamped certified, bounded p99 through the storm, exit-0
    recovery.  The bench leg runs a reduced request count (the full 200
    runs in CI's ``chaos-soak`` job) and reports the degraded- vs
    certified-tier latency split."""
    import subprocess
    import sys as _sys
    from pathlib import Path

    n_req = int(os.environ.get("BENCH_CHAOS_REQUESTS", "60"))
    seed = int(os.environ.get("BENCH_CHAOS_SEED", "0"))
    cmd = [_sys.executable,
           str(Path(__file__).resolve().parent / "scripts"
               / "chaos_soak.py"),
           "--seed", str(seed), "--requests", str(n_req),
           "--skip-sigkill"]
    t0 = time.time()
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=1800,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})
    if proc.returncode != 0:
        raise RuntimeError(
            f"chaos soak exited {proc.returncode}: "
            f"{proc.stderr.strip()[-300:]}")
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    soak = report["soak"]
    log(f"bench[serving_chaos]: {soak['requests']} seeded requests "
        f"under fault schedule in {time.time() - t0:.1f}s — "
        f"{soak['outcomes']['completed']} certified / "
        f"{soak['outcomes']['degraded']} degraded / "
        f"{soak['outcomes']['failed_typed']} typed failures, "
        f"p50/p99 {soak['latency_p50_s']}/{soak['latency_p99_s']}s; "
        f"recovery: {soak['resilience']['backend_recovery']['reinits']} "
        f"re-inits, {soak['resilience']['poison_quarantine']['quarantined']} "
        "poison quarantines; zero lost requests")
    return {
        "requests": soak["requests"],
        "outcomes": soak["outcomes"],
        "faults": soak["faults"],
        "latency_p50_s": soak["latency_p50_s"],
        "latency_p99_s": soak["latency_p99_s"],
        "resilience": soak["resilience"],
        "preempt": report.get("preempt"),
        "elapsed_s": round(time.time() - t0, 1),
    }


def serving_fleet_leg() -> dict:
    """Fleet-serving proof (service/fleet.py + router.py): the SAME
    mixed-structure workload served by a 1-replica and a 3-replica
    fleet (real ``serve`` subprocesses over file spools), then a
    failover drill — SIGKILL one replica mid-wave and measure the
    recovery.

    Published under ``legs.serving_fleet``: aggregate throughput 1 vs 3
    replicas (timed on the warm second wave; warm-start memory disabled
    so both passes honestly solve), structure-affinity hit rate on the
    repeat wave, and the router's failover-latency p50/p99 for the
    killed replica's recovered requests.

    Gates: zero lost / zero failed requests everywhere (exactly-once
    delivery through the kill), failover recovery under the request
    deadline; on a real accelerator host (CPU replicas share physical
    cores and cannot exhibit real scaling — ``gated_on_real_mesh``):
    aggregate 3-replica throughput >= 2x the single replica."""
    import shutil
    import signal as _signal
    import tempfile
    from pathlib import Path

    import jax

    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    from dervet_tpu.service import FleetRouter, ServiceJournal, \
        spawn_replica

    platform = jax.devices()[0].platform
    n_req = int(os.environ.get("BENCH_FLEET_REQUESTS", "12"))
    months = int(os.environ.get("BENCH_FLEET_MONTHS", "1"))
    lengths = (72, 96, 120, 144)
    workdir = Path(tempfile.mkdtemp(prefix="bench-fleet-"))

    def workload(tag, variant):
        out = {}
        for i in range(n_req):
            case = synthetic_sensitivity_cases(
                1, n=lengths[i % len(lengths)], months=months)[0]
            for t, _, keys in case.ders:
                if t == "Battery":
                    keys["ene_max_rated"] = \
                        8000.0 + 10.0 * i + 0.5 * variant
            out[f"{tag}{i:02d}"] = {0: case}
        return out

    log_handles = []

    def boot(root, n):
        reps = []
        for i in range(n):
            logf = open(root / f"r{i}.log", "w")
            log_handles.append(logf)
            reps.append(spawn_replica(
                root / f"r{i}", name=f"r{i}", backend="cpu",
                stdout=logf, stderr=logf,
                env={"DERVET_TPU_WARMSTART": "0"}))
        return reps

    def run_wave(router, reqs, deadline_s=600.0):
        futs = {rid: router.submit(c, request_id=rid,
                                   deadline_s=deadline_s)
                for rid, c in reqs.items()}
        return {rid: f.result(timeout=600) for rid, f in futs.items()}

    def pass_(tag, n_replicas):
        root = workdir / tag
        root.mkdir(parents=True)
        reps = boot(root, n_replicas)
        router = FleetRouter(reps, fleet_dir=root / "fleet",
                             heartbeat_timeout_s=5.0,
                             tick_s=0.05).start()
        try:
            run_wave(router, workload("w1.", 0))     # pays the compiles
            t0 = time.time()
            run_wave(router, workload("w2.", 1))     # timed, warm
            wall = time.time() - t0
            m = router.metrics()
            assert m["routing"]["failed"] == 0, m["routing"]
            log(f"bench[serving_fleet]: {tag} — {n_req} requests in "
                f"{wall:.2f}s ({n_req / wall:.2f} req/s), affinity hit "
                f"rate {m['routing']['affinity_hit_rate']}")
            return {"wall_s": wall, "router": router, "reps": reps,
                    "metrics": m}
        except BaseException:
            router.close()
            raise

    single = pass_("single", 1)
    single["router"].close()
    fleet = pass_("fleet", 3)

    # failover drill on the live 3-replica fleet: wave 3, kill one
    # replica once it has work admitted and unfinished.  Everything
    # from here runs under the router's finally: a drill failure must
    # not leak three live serve subprocesses into the rest of the bench
    router, reps = fleet["router"], fleet["reps"]
    try:
        futs = {rid: router.submit(c, request_id=rid, deadline_s=600.0)
                for rid, c in workload("w3.", 2).items()}
        victim = None
        kill_deadline = time.time() + 120
        while victim is None and time.time() < kill_deadline:
            for rep in reps:
                states = ServiceJournal.replay_path(
                    rep.spool / "service_journal.jsonl")
                if any(e["state"] == "admitted"
                       for e in states.values()):
                    victim = rep
                    break
            time.sleep(0.02)
        recovered = 0
        if victim is not None:
            victim.process.send_signal(_signal.SIGKILL)
        results = {rid: f.result(timeout=600) for rid, f in futs.items()}
        recovered = sum(1 for r in results.values() if r.recovered)
        m = router.metrics()
    finally:
        router.close()
        for fh in log_handles:
            fh.close()
    assert len(results) == n_req and m["routing"]["failed"] == 0, \
        "fleet drill lost or failed requests"

    speedup = single["wall_s"] / fleet["wall_s"]
    real_mesh = platform != "cpu"
    gates = {"zero_lost": len(results) == n_req,
             "zero_failed": m["routing"]["failed"] == 0,
             "kill_window_hit": victim is not None}
    if real_mesh:
        gates["throughput_2x_vs_single_replica"] = speedup >= 2.0
    ok = all(gates.values())
    fol = m["failover_latency_s"]
    log(f"bench[serving_fleet]: 3-replica {fleet['wall_s']:.2f}s vs "
        f"single {single['wall_s']:.2f}s ({speedup:.2f}x aggregate); "
        f"kill drill: victim {victim.name if victim else 'MISSED'}, "
        f"{recovered} recovered, failover latency p50/p99 "
        f"{fol['p50']}/{fol['p99']}s, "
        f"{m['routing']['duplicates_suppressed']} duplicates "
        f"suppressed; gates {'OK' if ok else 'FAIL'}"
        + ("" if real_mesh else
           " (2x gate skipped: CPU replicas share physical cores)"))
    if not ok:
        raise SystemExit(10)
    shutil.rmtree(workdir, ignore_errors=True)
    return {
        "requests_per_wave": n_req,
        "platform": platform,
        "single_replica_wall_s": round(single["wall_s"], 3),
        "fleet3_wall_s": round(fleet["wall_s"], 3),
        "aggregate_speedup": round(speedup, 2),
        "throughput_req_per_s": round(n_req / fleet["wall_s"], 2),
        "affinity_hit_rate":
            fleet["metrics"]["routing"]["affinity_hit_rate"],
        "failover": {
            "victim": victim.name if victim else None,
            "recovered_requests": recovered,
            "harvested": m["routing"]["harvested"],
            "rerouted": m["routing"]["rerouted"],
            "duplicates_suppressed":
                m["routing"]["duplicates_suppressed"],
            "latency_s": fol,
        },
        "gates": gates,
        "gated_on_real_mesh": real_mesh,
    }


def design_leg() -> dict:
    """BOOST design-service proof (``legs.design``): screen a large
    candidate population ordinally (loose PDHG on the batch axis,
    certification off, thread-local), certify only the top-k, and
    publish the two throughputs the ordinal-optimization economics rest
    on — SCREENING candidates/sec vs CERTIFIED solves/sec — plus the
    batching win (population / screening device dispatches; the solo
    floor is >= 1 dispatch per candidate).

    Gates: every finalist certified, the certified winner's screening
    rank within top-k, batching win >= 10x, and the warm repeat request
    compiling ZERO programs in both phases (persistent per-tier
    screening caches + the certified solver cache)."""
    from dervet_tpu.benchlib import synthetic_case
    from dervet_tpu.design import DERBounds, DesignSpec
    from dervet_tpu.service import ScenarioService

    population = int(os.environ.get("BENCH_DESIGN_POPULATION", "256"))
    top_k = int(os.environ.get("BENCH_DESIGN_TOPK", "8"))
    hours = int(os.environ.get("BENCH_DESIGN_HOURS", "168"))

    def case():
        c = synthetic_case()
        c.scenario["allow_partial_year"] = True
        c.datasets.time_series = c.datasets.time_series.iloc[:hours]
        return c

    spec = DesignSpec(
        bounds={("Battery", "1"): DERBounds(kw=(250.0, 2500.0),
                                            kwh=(500.0, 9000.0))},
        population=population, top_k=top_k, refine_rounds=1)
    svc = ScenarioService(backend="jax", max_wait_s=0.05)
    svc.start()
    try:
        t0 = time.time()
        frontier = svc.submit_design(case(), spec,
                                     request_id="bench-design").result()
        t_cold = time.time() - t0
        compiles_before = svc.metrics()["rounds"]["compile_events"]
        t0 = time.time()
        warm = svc.submit_design(case(), spec,
                                 request_id="bench-design-warm").result()
        t_warm = time.time() - t0
        warm_round_compiles = (svc.metrics()["rounds"]["compile_events"]
                               - compiles_before)
        m = svc.metrics()
    finally:
        svc.close()

    screen_s = warm.screen["screen_s"]
    cand_per_s = warm.screen["candidates_per_s"]
    # certified throughput: the warm request's wall minus its screening
    # wall is the certified finalist phase (fresh scenarios, full
    # tolerances, escalation ladder, float64 certificates)
    certified_s = max(1e-9, t_warm - screen_s)
    certified_per_s = round(top_k / certified_s, 2)
    dispatches = warm.screen["dispatches"]
    batching_win = population / max(1, dispatches)
    ok = (frontier.all_finalists_certified
          and warm.all_finalists_certified
          and 1 <= int(warm.winner["screen_rank"]) <= top_k
          # rank-correlation is the REAL ordinal-health gate (finalists
          # are the screen's own top-k, so the rank bound alone only
          # catches bookkeeping bugs)
          and (warm.rank_correlation is None
               or warm.rank_correlation >= 0.5)
          and batching_win >= 10
          and warm.screen["compile_events"] == 0
          and warm_round_compiles == 0)
    log(f"bench[design]: {population}-candidate population -> top-{top_k} "
        f"certified frontier; cold {t_cold:.1f}s, warm {t_warm:.1f}s; "
        f"screening {cand_per_s} cand/s vs certified "
        f"{certified_per_s} solves/s "
        f"({(cand_per_s or 0) / max(certified_per_s, 1e-9):.0f}x); "
        f"batching win {batching_win:.0f}x ({dispatches} dispatches), "
        f"warm compiles {warm.screen['compile_events']}+"
        f"{warm_round_compiles}; rank corr {warm.rank_correlation}; "
        f"gates: {'OK' if ok else 'FAIL'}")
    if not ok:
        raise SystemExit(7)
    return {
        "population": population, "top_k": top_k, "hours": hours,
        "cold_request_s": round(t_cold, 2),
        "warm_request_s": round(t_warm, 2),
        "screen_candidates_per_s": cand_per_s,
        "certified_solves_per_s": certified_per_s,
        "screen_vs_certified_x": round(
            (cand_per_s or 0) / certified_per_s, 1),
        "screen_dispatches": int(dispatches),
        "batching_win_x": round(batching_win, 1),
        "warm_compile_events": int(warm.screen["compile_events"]
                                   + warm_round_compiles),
        "rank_correlation": warm.rank_correlation,
        "winner_screen_rank": int(warm.winner["screen_rank"]),
        "finalists_certified": bool(warm.all_finalists_certified),
        "design_metrics": {k: m["design"][k] for k in
                           ("requests", "candidates", "finalists",
                            "screen_rounds", "screen_s")},
    }


def monte_carlo_leg() -> dict:
    """Uncertainty-product proof (``legs.monte_carlo``,
    dervet_tpu/stochastic): one N-sample Monte-Carlo valuation request
    through the service — the whole sample mass screens in ONE
    cert-off dispatch round, the quantile/CVaR-pinning samples re-solve
    fresh at full certified tolerances, and the distribution publishes
    with float64 host-side stats.

    Publishes the two tier throughputs the product's economics rest on
    (SCREENING samples/s vs CERTIFIED samples/s) plus the batching win
    (samples / device dispatches) and the amortization curve (cold vs
    warm compile events).

    Gates: every pinning sample certified, the screening mass never
    cert-stamped, batching win >= 10x, warm repeat compiling ZERO
    programs AND serializing a byte-identical mc_distribution.json
    (the fixed-seed determinism contract)."""
    from dervet_tpu.benchlib import synthetic_case
    from dervet_tpu.service import ScenarioService
    from dervet_tpu.stochastic import MCSpec

    samples = int(os.environ.get("BENCH_MC_SAMPLES", "512"))
    hours = int(os.environ.get("BENCH_MC_HOURS", "72"))
    spec = MCSpec(n_samples=samples, seed=11)

    def case():
        c = synthetic_case()
        c.scenario["allow_partial_year"] = True
        c.datasets.time_series = c.datasets.time_series.iloc[:hours]
        return c

    svc = ScenarioService(backend="jax", max_wait_s=0.05)
    svc.start()
    try:
        t0 = time.time()
        res = svc.submit_montecarlo(case(), spec,
                                    request_id="bench-mc").result()
        t_cold = time.time() - t0
        t0 = time.time()
        warm = svc.submit_montecarlo(case(), spec,
                                     request_id="bench-mc").result()
        t_warm = time.time() - t0
        m = svc.metrics()
    finally:
        svc.close()

    dispatches = int(res.engine["dispatches"])
    batching_win = samples / max(1, dispatches)
    byte_identical = warm.to_json() == res.to_json()
    ok = (res.pinning_all_certified
          and not res.engine["certification_stamped_screening"]
          and batching_win >= 10
          and warm.engine["compile_events"] == 0
          and byte_identical)
    log(f"bench[monte_carlo]: {samples} samples -> "
        f"{res.tier_mix['certified']} certified-pinning "
        f"({res.tier_mix['quarantined']} quarantined); cold "
        f"{t_cold:.1f}s, warm {t_warm:.1f}s; screening "
        f"{res.engine['samples_per_s_screening']} samples/s vs "
        f"certified {res.engine['samples_per_s_certified']}; batching "
        f"win {batching_win:.0f}x ({dispatches} dispatches), compiles "
        f"{res.engine['compile_events']} cold -> "
        f"{warm.engine['compile_events']} warm; byte-identical "
        f"{byte_identical}; gates: {'OK' if ok else 'FAIL'}")
    if not ok:
        raise SystemExit(7)
    return {
        "samples": samples, "hours": hours,
        "cold_request_s": round(t_cold, 2),
        "warm_request_s": round(t_warm, 2),
        "samples_per_s_screening":
            res.engine["samples_per_s_screening"],
        "samples_per_s_certified":
            res.engine["samples_per_s_certified"],
        "dispatches": dispatches,
        "batching_win_x": round(batching_win, 1),
        "cold_compile_events": int(res.engine["compile_events"]),
        "warm_compile_events": int(warm.engine["compile_events"]),
        "byte_identical_repeat": bool(byte_identical),
        "tier_mix": dict(res.tier_mix),
        "cvar_alpha": res.stats["cvar_alpha"],
        "mc_metrics": {k: m["monte_carlo"][k] for k in
                       ("requests", "samples", "certified_samples",
                        "quarantined")},
    }


def portfolio_leg() -> dict:
    """Portfolio co-optimization proof (``legs.portfolio``,
    dervet_tpu/portfolio): an N-site fleet coupled by a shared
    aggregate-export cap, solved by the dual-decomposed outer loop
    whose inner step is ONE ``run_dispatch`` batch over every site's
    window LPs.

    Three measurements: the INDEPENDENT baseline (the same sites
    uncoupled — also round 0 of the dual loop), the COUPLED dual loop
    (outer rounds to gap tolerance, per-round inner iters p50 with the
    dual_iterate warm seeds), and a COLD CONTROL (the final round's
    exact price-shifted problem re-dispatched with the warm-start
    memory off — the honest A/B for the seeding win).

    Gates: convergence within the outer budget at the gap tolerance,
    100% per-site certification, ZERO XLA compile events after outer
    round 1 (the loop's whole point — compiles amortize across rounds),
    >= 2x median inner-iteration reduction on outer rounds >= 2 vs the
    cold control, and the kernel-fallback gate.  Aggregate-throughput
    scaling claims (the dual loop's amortized windows/s vs independent)
    are ``gated_on_real_mesh`` — CPU CI shares cores and proves
    structure, not scaling."""
    import numpy as _np

    from dervet_tpu.portfolio import PortfolioSpec, solve_portfolio
    from dervet_tpu.portfolio.service import synthetic_portfolio_members
    from dervet_tpu.portfolio.solve import (build_site_scenarios,
                                            validate_portfolio_section)
    from dervet_tpu.scenario.scenario import SolverCache, run_dispatch

    import jax as _jax
    sites = int(os.environ.get("BENCH_PORTFOLIO_SITES", "64"))
    hours = int(os.environ.get("BENCH_PORTFOLIO_HOURS", "336"))
    window = int(os.environ.get("BENCH_PORTFOLIO_WINDOW", "168"))
    gap_tol = float(os.environ.get("BENCH_PORTFOLIO_GAP", "1e-3"))
    max_outer = int(os.environ.get("BENCH_PORTFOLIO_MAX_OUTER", "30"))

    def members():
        return synthetic_portfolio_members(sites, hours=hours,
                                           window=window)

    # independent baseline: the identical fleet, uncoupled (a cap no
    # dispatch can reach) — one run_dispatch, genuine cold iterations
    t0 = time.time()
    probe = solve_portfolio(
        PortfolioSpec(members=members(), export_cap_kw=1e9, max_outer=1),
        backend="jax")
    t_indep = time.time() - t0
    indep_round = probe.rounds[0]
    n_windows = int(indep_round["windows"])
    cold_p50 = int(indep_round["iters_p50"])
    cap = float(probe.aggregate["net_export"].max()) - 500.0 * sites

    t0 = time.time()
    res = solve_portfolio(
        PortfolioSpec(members=members(), export_cap_kw=cap,
                      max_outer=max_outer, gap_tol=gap_tol),
        backend="jax")
    t_coupled = time.time() - t0
    validate_portfolio_section(res.run_health["portfolio"])
    check_kernel_gate(res.solve_ledger, "portfolio")

    # cold control: the FINAL round's price-shifted problem without the
    # warm-start memory — same data, seeded vs cold, nothing else moves
    ctrl_scens = build_site_scenarios(
        PortfolioSpec(members=members(), export_cap_kw=cap))
    for s in ctrl_scens.values():
        s.coupling_price = res.price
    t0 = time.time()
    run_dispatch(list(ctrl_scens.values()), backend="jax",
                 solver_cache=SolverCache(pad_grid=True))
    t_ctrl = time.time() - t0
    ctrl_led = next(iter(ctrl_scens.values())).solve_metadata[
        "solve_ledger"]
    ctrl_p50 = int(ctrl_led["iters"]["p50"])

    # a fully exact-substituted round records iters_p50 0 (zero device
    # work); cpu-backend ledgers carry None — drop those rather than
    # crash the gate arithmetic
    late = [int(r["iters_p50"]) for r in res.rounds[2:]
            if r["iters_p50"] is not None]
    seeded_p50 = float(_np.median(late)) if late else float("nan")
    reduction_x = ctrl_p50 / seeded_p50 if late and seeded_p50 else 0.0
    late_compiles = sum(int(r["compile_events"])
                        for r in res.rounds[1:])
    windows_total = sum(int(r["windows"]) for r in res.rounds)
    coupled_wps = windows_total / t_coupled
    indep_wps = n_windows / t_indep
    cert = res.certification
    platform = _jax.devices()[0].platform
    real_mesh = platform != "cpu"

    gates = {
        "converged_within_budget": bool(res.converged),
        "gap_below_tol": res.gap_rel <= gap_tol,
        "all_site_windows_certified":
            bool(cert["per_site"]["all_certified"]),
        "zero_compiles_after_round1": late_compiles == 0,
        # the reduction gate only applies when the dual loop actually
        # iterated — a 1-2 round convergence (barely-binding cap) has
        # no warm rounds to measure and must not read as a regression
        "dual_warm_2x_vs_cold": (reduction_x >= 2.0 if late else True),
    }
    if real_mesh:
        # amortized aggregate throughput only means scaling on hardware
        # that actually parallelizes the batch axis
        gates["amortized_throughput_ge_independent"] = \
            coupled_wps >= indep_wps
    ok = all(gates.values())
    log(f"bench[portfolio]: {sites} sites x {n_windows // sites} "
        f"windows, shared export cap {cap:.0f} kW; independent "
        f"{t_indep:.1f}s (cold iters p50 {cold_p50}) -> coupled "
        f"{res.outer_rounds} outer rounds in {t_coupled:.1f}s, gap "
        f"{res.gap_rel:.2e}, {cert['coupling_rows']['export_cap']['binding']} "
        f"binding rows; dual-warm iters p50 {seeded_p50:.0f} vs cold "
        f"control {ctrl_p50} = {reduction_x:.2f}x (gate >= 2x), "
        f"{late_compiles} compiles after round 1; gates "
        f"{'OK' if ok else 'FAIL: ' + str(gates)}")
    if not ok:
        raise SystemExit(11)
    return {
        "sites": sites, "hours": hours, "window": window,
        "windows_per_round": n_windows,
        "export_cap_kw": round(cap, 1),
        "gap_tol": gap_tol,
        "outer_rounds": res.outer_rounds,
        "gap_rel": res.gap_rel,
        "dual_rescales": res.dual_rescales,
        "binding_rows":
            cert["coupling_rows"]["export_cap"]["binding"],
        "verdict": cert["verdict"],
        "independent": {"wall_s": round(t_indep, 2),
                        "iters_p50_cold": cold_p50,
                        "windows_per_s": round(indep_wps, 2)},
        "coupled": {"wall_s": round(t_coupled, 2),
                    "windows_total": windows_total,
                    "windows_per_s": round(coupled_wps, 2),
                    "amortized_vs_independent_x":
                        round(coupled_wps / indep_wps, 2)},
        "cold_control": {"wall_s": round(t_ctrl, 2),
                         "iters_p50": ctrl_p50},
        "dual_warm": {"iters_p50_rounds_ge2": seeded_p50,
                      "reduction_x": round(reduction_x, 2),
                      "compiles_after_round1": late_compiles},
        "rounds": [{k: r[k] for k in
                    ("round", "iters_p50", "seeded", "dual_iterate",
                     "substituted", "compile_events", "gap_rel",
                     "wall_s")} for r in res.rounds],
        "gates": gates,
        "gated_on_real_mesh": real_mesh,
    }


def portfolio_scale_leg() -> dict:
    """Portfolio scale-out proof (``legs.portfolio_scale``, PR 15): the
    two compounding wall-time attacks on the dual loop, A/B'd at the
    BENCH_r07 64-site shape.

    (1) STABILIZED MASTER: the in-out / proximal-level dual step
    (``PortfolioSpec.master_stabilization``) vs the PR-13 three-regime
    control (``DERVET_TPU_PORTFOLIO_STABILIZE=0``) — outer rounds to
    the 1e-3 gap, gate >= 40% fewer.

    (2) FLEET-SHARDED ROUNDS: one dual round's member batch split into
    N structure-aware shards dispatched concurrently (the in-process
    executor; the fleet-replica transport is drilled by
    ``scripts/portfolio_fleet_smoke.py``) — amortized windows/s vs the
    monolithic round at a FIXED round budget.  The throughput gate is
    ``gated_on_real_mesh``: CPU CI time-slices one socket across the
    shard workers and proves structure, not scaling.

    Plus the parity gate both attacks must preserve: on the exact cpu
    backend a sharded solve's answer (duals, aggregate, objective) is
    IDENTICAL to the monolithic one for a fixed shard plan — per-site
    columns and costs do not depend on which shard solved them.

    And the fleet-transport bytes-on-wire A/B (the replica-side shard
    case cache): after round 0 seeds each replica, a dual round ships
    one price vector + plan fingerprint per shard instead of
    re-pickling every site's payload — gate <= 20% of the full-payload
    round's bytes."""
    import numpy as _np

    from dervet_tpu.portfolio import PortfolioSpec, solve_portfolio
    from dervet_tpu.portfolio.service import synthetic_portfolio_members
    from dervet_tpu.portfolio.solve import validate_portfolio_section

    import jax as _jax
    sites = int(os.environ.get("BENCH_PFSCALE_SITES", "64"))
    hours = int(os.environ.get("BENCH_PFSCALE_HOURS", "336"))
    window = int(os.environ.get("BENCH_PFSCALE_WINDOW", "168"))
    gap_tol = float(os.environ.get("BENCH_PFSCALE_GAP", "1e-3"))
    max_outer = int(os.environ.get("BENCH_PFSCALE_MAX_OUTER", "40"))
    n_shards = int(os.environ.get("BENCH_PFSCALE_SHARDS", "4"))
    shard_rounds = int(os.environ.get("BENCH_PFSCALE_SHARD_ROUNDS", "4"))

    def members():
        return synthetic_portfolio_members(sites, hours=hours,
                                           window=window)

    probe = solve_portfolio(
        PortfolioSpec(members=members(), export_cap_kw=1e9, max_outer=1),
        backend="jax")
    cap = float(probe.aggregate["net_export"].max()) - 500.0 * sites

    def spec(**kw):
        base = dict(export_cap_kw=cap, max_outer=max_outer,
                    gap_tol=gap_tol)
        base.update(kw)
        return PortfolioSpec(members=members(), **base)

    # ---- A/B 1: stabilized vs three-regime control -------------------
    # the switch is read per call, so a value left in the operator's
    # environment would silently turn the "stabilized" arm into a
    # second control — clear it for the A arm, force "0" for B, restore
    env_key = "DERVET_TPU_PORTFOLIO_STABILIZE"
    env_prev = os.environ.pop(env_key, None)
    try:
        t0 = time.time()
        stab = solve_portfolio(spec(), backend="jax")
        t_stab = time.time() - t0
        validate_portfolio_section(stab.run_health["portfolio"])
        check_kernel_gate(stab.solve_ledger, "portfolio_scale")
        os.environ[env_key] = "0"
        t0 = time.time()
        ctrl = solve_portfolio(spec(), backend="jax")
        t_ctrl = time.time() - t0
    finally:
        if env_prev is None:
            os.environ.pop(env_key, None)
        else:
            os.environ[env_key] = env_prev
    rounds_cut = (1.0 - stab.outer_rounds / ctrl.outer_rounds
                  if ctrl.outer_rounds else 0.0)
    regimes: dict = {}
    for r in stab.rounds:
        regimes[str(r["regime"])] = regimes.get(str(r["regime"]), 0) + 1

    # ---- A/B 2: sharded vs monolithic rounds at a fixed budget -------
    t0 = time.time()
    mono = solve_portfolio(spec(max_outer=shard_rounds, gap_tol=1e-12),
                           backend="jax")
    t_mono = time.time() - t0
    t0 = time.time()
    shrd = solve_portfolio(spec(max_outer=shard_rounds, gap_tol=1e-12,
                                shards=n_shards), backend="jax")
    t_shard = time.time() - t0
    mono_w = sum(int(r["windows"]) for r in mono.rounds)
    shard_w = sum(int(r["windows"]) for r in shrd.rounds)
    mono_wps = mono_w / t_mono
    shard_wps = shard_w / t_shard
    # per-round wall with round 0 (compiles) dropped: the steady-state
    # per-round-wall / shards quotient the headline number multiplies
    mono_round_s = float(_np.mean([r["wall_s"]
                                   for r in mono.rounds[1:]])) \
        if len(mono.rounds) > 1 else float("nan")
    shard_round_s = float(_np.mean([r["wall_s"]
                                    for r in shrd.rounds[1:]])) \
        if len(shrd.rounds) > 1 else float("nan")

    # ---- parity: sharded == monolithic bytes on the exact backend ----
    small = synthetic_portfolio_members(16, hours=48, window=24,
                                        seed=0, pv_kw=9000.0)
    sprobe = solve_portfolio(
        PortfolioSpec(members=dict(small), export_cap_kw=1e9,
                      max_outer=1), backend="cpu")
    scap = float(sprobe.aggregate["net_export"].max()) - 4000.0
    pkw = dict(export_cap_kw=scap, gap_tol=1e-6, feas_tol=1e-7,
               max_outer=40)
    pm = solve_portfolio(PortfolioSpec(members=dict(small), **pkw),
                         backend="cpu")
    psh = solve_portfolio(PortfolioSpec(members=dict(small),
                                        shards=n_shards, **pkw),
                          backend="cpu")
    parity_rel = abs(pm.primal_objective - psh.primal_objective) \
        / (1.0 + abs(pm.primal_objective))
    duals_equal = all(
        _np.array_equal(pm.duals[k], psh.duals[k]) for k in pm.duals)

    # ---- bytes-on-wire: reference rounds on the fleet transport ------
    # the replica-side shard case cache (service/server.py): round 0
    # ships full site payloads and seeds each replica's cache; every
    # later round ships one dual-price vector + a plan fingerprint per
    # shard.  Measured on the 16-site shape over LocalReplica
    # transport; the byte counts are the pickled request payloads the
    # spool transport would write.
    from dervet_tpu.portfolio.shard import FleetShardExecutor
    from dervet_tpu.service.fleet import LocalReplica
    from dervet_tpu.service.router import FleetRouter
    from dervet_tpu.service.server import ScenarioService
    wire_services = [ScenarioService(backend="cpu", max_wait_s=0.0)
                     for _ in range(2)]
    for s in wire_services:
        s.start()
    wire_router = FleetRouter(
        [LocalReplica(f"w{i}", s) for i, s in enumerate(wire_services)],
        heartbeat_timeout_s=5.0, hedging=False).start()
    try:
        wm = dict(small)
        wkeys = sorted(wm, key=str)
        mid = len(wkeys) // 2
        wex = FleetShardExecutor(wm, [wkeys[:mid], wkeys[mid:]],
                                 wire_router, backend="cpu",
                                 portfolio_id="pfwire",
                                 deadline_s=600.0)
        wprice = _np.zeros(48)
        for rnd in range(3):
            wex.dispatch_round(wprice, rnd)
        wire_rounds = list(wex.wire_bytes_rounds)
    finally:
        wire_router.close(terminate_replicas=False)
        for s in wire_services:
            s.close()
    wire_ratio = wire_rounds[1] / max(wire_rounds[0], 1)

    platform = _jax.devices()[0].platform
    real_mesh = platform != "cpu"
    gates = {
        "both_converged": bool(stab.converged and ctrl.converged),
        "stabilized_rounds_cut_ge_40pct": rounds_cut >= 0.40,
        "sharded_parity_exact": bool(duals_equal) and parity_rel < 1e-9,
        "ref_round_bytes_le_20pct_of_full": wire_ratio <= 0.20,
    }
    if real_mesh:
        gates["sharded_amortized_throughput_ge_monolithic"] = \
            shard_wps >= mono_wps
    ok = all(gates.values())
    log(f"bench[portfolio_scale]: {sites} sites, gap {gap_tol:g}: "
        f"stabilized {stab.outer_rounds} rounds ({t_stab:.1f}s) vs "
        f"control {ctrl.outer_rounds} ({t_ctrl:.1f}s) = "
        f"{rounds_cut:.0%} cut (gate >= 40%); sharded x{n_shards} "
        f"round {shard_round_s:.2f}s vs monolithic {mono_round_s:.2f}s "
        f"({shard_wps:.1f} vs {mono_wps:.1f} windows/s, real-mesh "
        f"gated); parity rel {parity_rel:.2e} duals_equal "
        f"{duals_equal}; ref-round wire {wire_rounds[1]} B vs full "
        f"{wire_rounds[0]} B ({wire_ratio:.1%}); gates "
        f"{'OK' if ok else 'FAIL: ' + str(gates)}")
    if not ok:
        raise SystemExit(12)
    return {
        "sites": sites, "hours": hours, "window": window,
        "gap_tol": gap_tol, "export_cap_kw": round(cap, 1),
        "stabilized": {"outer_rounds": stab.outer_rounds,
                       "gap_rel": stab.gap_rel,
                       "wall_s": round(t_stab, 2),
                       "regimes": regimes},
        "control": {"outer_rounds": ctrl.outer_rounds,
                    "gap_rel": ctrl.gap_rel,
                    "wall_s": round(t_ctrl, 2)},
        "rounds_cut": round(rounds_cut, 3),
        "sharded": {"shards": n_shards,
                    "rounds_measured": shard_rounds,
                    "round_wall_s_steady": round(shard_round_s, 3),
                    "windows_per_s": round(shard_wps, 2),
                    "monolithic_round_wall_s_steady":
                        round(mono_round_s, 3),
                    "monolithic_windows_per_s": round(mono_wps, 2),
                    "throughput_x": round(shard_wps / mono_wps, 2)},
        "parity_cpu_16_sites": {"rel_objective": parity_rel,
                                "duals_equal": bool(duals_equal)},
        "shard_wire_bytes": {"rounds": wire_rounds,
                             "ref_to_full_ratio": round(wire_ratio, 4)},
        "stab_rounds": [{k: r[k] for k in
                         ("round", "regime", "step", "gap_rel",
                          "wall_s")} for r in stab.rounds],
        "ctrl_rounds": [{k: r[k] for k in
                         ("round", "regime", "step", "gap_rel",
                          "wall_s")} for r in ctrl.rounds],
        "gates": gates,
        "gated_on_real_mesh": real_mesh,
    }


def request_cache_leg() -> dict:
    """Request-level memoization proof (``legs.request_cache``, the
    router's admission plane — ``service/reqcache.py``): the content-
    addressed result cache, fleet-wide in-flight dedup, and delta
    solves, measured against the cold path on a real 2-replica spool
    fleet.

    Published: cold vs cache-hit latency p50/p99 (a hit answers from
    the router with zero replica dispatches), the dedup ratio for N
    identical co-pending requests (one solve, N deliveries), and the
    delta windows-resolved fraction for a one-window edit.

    Gates: every repeat request a cache hit; hit p99 at least 10x
    under the cold p50; N co-pending identical requests coalesce to
    ONE replica solve; the delta diff localizes a one-window edit to
    <= 10% of the horizon's windows; zero failed requests."""
    import copy
    import shutil
    import tempfile
    from pathlib import Path

    import numpy as _np

    from dervet_tpu.benchlib import synthetic_sensitivity_cases
    from dervet_tpu.service import FleetRouter, ServiceJournal, \
        spawn_replica

    n_req = int(os.environ.get("BENCH_REQCACHE_REQUESTS", "6"))
    n_dup = int(os.environ.get("BENCH_REQCACHE_DUPLICATES", "4"))
    months = int(os.environ.get("BENCH_REQCACHE_MONTHS", "1"))
    lengths = (48, 72, 96, 120)
    workdir = Path(tempfile.mkdtemp(prefix="bench-reqcache-"))
    log_handles = []

    def workload(tag):
        out = {}
        for i in range(n_req):
            case = synthetic_sensitivity_cases(
                1, n=lengths[i % len(lengths)], months=months)[0]
            for t, _, keys in case.ders:
                if t == "Battery":
                    keys["ene_max_rated"] = 8000.0 + 10.0 * i
            out[f"{tag}{i:02d}"] = {0: case}
        return out

    def run_wave(router, reqs):
        futs = {rid: router.submit(c, request_id=rid, deadline_s=600.0)
                for rid, c in reqs.items()}
        return {rid: f.result(timeout=600) for rid, f in futs.items()}

    reps = []
    for i in range(2):
        logf = open(workdir / f"r{i}.log", "w")
        log_handles.append(logf)
        reps.append(spawn_replica(workdir / f"r{i}", name=f"r{i}",
                                  backend="cpu", stdout=logf,
                                  stderr=logf))
    router = FleetRouter(reps, fleet_dir=workdir / "fleet",
                         heartbeat_timeout_s=5.0, tick_s=0.05).start()
    try:
        cold = run_wave(router, workload("c."))
        cold_lat = _np.array(sorted(r.latency_s for r in cold.values()))
        warm = run_wave(router, workload("h."))
        hit_lat = _np.array(sorted(r.latency_s for r in warm.values()))
        hits = sum(1 for r in warm.values() if r.cached)

        # dedup: N identical co-pending requests
        dup_case = {0: synthetic_sensitivity_cases(
            1, n=60, months=months)[0]}
        dup_futs = {f"dup{i}": router.submit(
                        copy.deepcopy(dup_case), request_id=f"dup{i}",
                        deadline_s=600.0) for i in range(n_dup)}
        dup_res = {rid: f.result(timeout=600)
                   for rid, f in dup_futs.items()}
        admitted = set()
        for rep in reps:
            path = rep.spool / "service_journal.jsonl"
            if path.exists():
                admitted.update(ServiceJournal.replay_path(path))
        dup_solves = len(admitted & set(dup_futs))

        # delta: one-window edit on a 24h-window month
        base = {0: synthetic_sensitivity_cases(1, n=24, months=1)[0]}
        router.submit(copy.deepcopy(base), request_id="delta.base",
                      deadline_s=600.0).result(timeout=600)
        edited = copy.deepcopy(base)
        ts = edited[0].datasets.time_series
        ts.iloc[30, ts.columns.get_loc("DA Price ($/kWh)")] += 0.05
        router.submit_delta(base, edited, request_id="delta.edit",
                            deadline_s=600.0).result(timeout=600)
        events = [json.loads(ln) for ln in
                  (workdir / "fleet" /
                   "fleet_journal.jsonl").read_text().splitlines()]
        note = next(e for e in events if e["event"] == "delta"
                    and e["rid"] == "delta.edit")
        m = router.metrics()
    finally:
        router.close()
        for fh in log_handles:
            fh.close()

    cold_p50 = float(_np.percentile(cold_lat, 50))
    cold_p99 = float(_np.percentile(cold_lat, 99))
    hit_p50 = float(_np.percentile(hit_lat, 50))
    hit_p99 = float(_np.percentile(hit_lat, 99))
    delta_fraction = (note["windows_changed"] / note["windows_total"]
                      if note["windows_total"] else 1.0)
    gates = {
        "zero_failed": m["routing"]["failed"] == 0,
        "all_repeats_hit": hits == n_req,
        "hit_p99_10x_under_cold_p50": hit_p99 < 0.1 * cold_p50,
        "dedup_single_solve": dup_solves == 1
        and m["routing"]["duplicates_coalesced"] == n_dup - 1,
        "delta_fraction_le_10pct": delta_fraction <= 0.10,
    }
    ok = all(gates.values())
    log(f"bench[request_cache]: cold p50/p99 {cold_p50:.2f}/"
        f"{cold_p99:.2f}s vs hit {hit_p50 * 1e3:.1f}/"
        f"{hit_p99 * 1e3:.1f}ms ({hits}/{n_req} hits); dedup "
        f"{n_dup}->{dup_solves} solve; delta resolved "
        f"{delta_fraction:.1%} of windows; gates "
        f"{'OK' if ok else 'FAIL: ' + str(gates)}")
    if not ok:
        raise SystemExit(13)
    shutil.rmtree(workdir, ignore_errors=True)
    return {
        "requests": n_req,
        "cold_latency_s": {"p50": round(cold_p50, 3),
                           "p99": round(cold_p99, 3)},
        "hit_latency_s": {"p50": round(hit_p50, 5),
                          "p99": round(hit_p99, 5)},
        "hit_speedup_p50": round(cold_p50 / max(hit_p50, 1e-9), 1),
        "cache": m["request_cache"],
        "dedup": {"co_pending": n_dup, "replica_solves": dup_solves,
                  "coalesced": m["routing"]["duplicates_coalesced"]},
        "delta": {"windows_total": note["windows_total"],
                  "windows_changed": note["windows_changed"],
                  "resolved_fraction": round(delta_fraction, 4)},
        "gates": gates,
    }


def real_case_leg() -> None:
    """Tie the perf number to validated numerics (VERDICT r2 #9): run a
    REAL reference input (Usecase2 step2 — fixed-size retail + demand-charge
    + User min-SOE dispatch, the golden-validated case whose windows
    genuinely exercise the batched PDHG path) on the jax backend and
    cross-check its NPV against the CPU exact solver in the same process.
    Results go to stderr; the primary metric line stays the contract."""
    from pathlib import Path

    ref = Path("/root/reference/test/test_validation_report_sept1/"
               "Model_params/Usecase2/"
               "Model_Parameters_Template_Usecase3_Planned_ES_Step2.csv")
    if not ref.exists():
        log("bench[real-case]: reference input not available — skipped")
        return
    from dervet_tpu.api import DERVET

    base = Path("/root/reference")
    t0 = time.time()
    inst_j = DERVET(ref, base_path=base).solve(backend="jax").instances[0]
    t_jax = time.time() - t0
    t0 = time.time()
    inst_c = DERVET(ref, base_path=base).solve(backend="cpu").instances[0]
    t_cpu = time.time() - t0
    npv_j = float(inst_j.npv_df["Lifetime Present Value"].iloc[0])
    npv_c = float(inst_c.npv_df["Lifetime Present Value"].iloc[0])
    rel = abs(npv_j - npv_c) / max(1.0, abs(npv_c))
    ok = rel < 1e-2
    log(f"bench[real-case]: UC2-step2 jax {t_jax:.1f}s vs cpu {t_cpu:.1f}s; "
        f"NPV jax {npv_j:,.2f} vs cpu {npv_c:,.2f} (rel err {rel:.2e}; "
        f"gate 1e-2): {'OK' if ok else 'FAIL'}")
    if not ok:
        raise SystemExit(2)     # the gate must fail scripted runs, not log


if __name__ == "__main__":
    main()
